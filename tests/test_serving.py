"""paddle_tpu.serving — continuous-batching engine, block pool,
scheduler, metrics, endpoint.

The ISSUE 2 done bar lives here: greedy engine outputs are TOKEN-EXACT
with sequential ``generate()`` (including across preemption), the
compiled decode step never retraces after warmup, and the block pool
round-trips every block through a full workload.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (FINISHED, QUEUED, AdmissionError,
                                BlockKVPool, Engine, PoolExhausted,
                                Request, ServingConfig)


# One model for the whole module: every compiled step (prefill per
# bucket, decode per engine config) is cached on it by weights
# fingerprint, so tests share executables instead of recompiling.
@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _prompts(lengths, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=(L,)).astype(np.int32)
            for L in lengths]


def _reference(model, prompt, **kw):
    """Sequential greedy generate() — the parity oracle."""
    out = model.generate(paddle.to_tensor(prompt[None, :]),
                         temperature=0.0, use_static_cache=True, **kw)
    return np.asarray(out.numpy())[0]


def _config(**kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_queue_len", 16)
    return ServingConfig(**kw)


# ---------------------------------------------------------------------------
# BlockKVPool
# ---------------------------------------------------------------------------

class TestBlockKVPool:
    def _pool(self, num_blocks=8, block_size=4):
        return BlockKVPool(num_layers=2, num_blocks=num_blocks,
                           block_size=block_size, kv_heads=2, head_dim=4)

    def test_block0_reserved(self):
        pool = self._pool()
        got = pool.allocate("r", pool.capacity_blocks)
        assert 0 not in got
        assert pool.num_free == 0

    def test_allocate_free_roundtrip(self):
        pool = self._pool()
        a = pool.allocate("a", 3)
        b = pool.allocate("b", 2)
        assert pool.num_used == 5
        assert sorted(pool.owned_by("a")) == sorted(a)
        pool.free_request("a")
        pool.free(b)
        assert pool.num_free == pool.capacity_blocks
        pool.check_leaks()

    def test_double_free_raises(self):
        pool = self._pool()
        blocks = pool.allocate("a", 1)
        pool.free(blocks)
        with pytest.raises(ValueError, match="double free"):
            pool.free(blocks)

    def test_exhaustion_raises_and_keeps_state(self):
        pool = self._pool(num_blocks=4)
        pool.allocate("a", 2)
        with pytest.raises(PoolExhausted):
            pool.allocate("b", 2)
        assert pool.num_free == 1  # failed allocation took nothing

    def test_blocks_for_ceil_division(self):
        pool = self._pool(block_size=4)
        assert [pool.blocks_for(n) for n in (1, 4, 5, 8, 9)] == \
            [1, 1, 2, 2, 3]

    def test_check_leaks_reports_owner(self):
        pool = self._pool()
        pool.allocate("leaky", 1)
        with pytest.raises(AssertionError, match="leaky"):
            pool.check_leaks()


# ---------------------------------------------------------------------------
# Engine: the parity + no-retrace done bar
# ---------------------------------------------------------------------------

class TestEngineParity:
    def test_greedy_parity_mixed_lengths(self, model):
        """Continuous-batched greedy == sequential generate(), token for
        token, across prompt lengths that pad to different buckets."""
        prompts = _prompts([3, 7, 5, 11, 4, 6])
        refs = [_reference(model, p, max_new_tokens=8) for p in prompts]
        eng = Engine(model, _config())
        outs = eng.generate(prompts, max_new_tokens=8)
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)

    def test_never_retraces_after_warmup(self, model):
        """The compiled decode step holds ONE jit cache entry no matter
        how requests churn through the bucket (the H101 property the
        engine asserts every iteration under strict_no_retrace)."""
        eng = Engine(model, _config())
        eng.generate(_prompts([3, 5]), max_new_tokens=4)
        warm = eng.decode_cache_size()
        eng.generate(_prompts([9, 2, 7], seed=3), max_new_tokens=6)
        assert eng.decode_cache_size() == warm

    def test_no_block_leaks_after_workload(self, model):
        eng = Engine(model, _config())
        eng.generate(_prompts([3, 7, 5, 11, 4]), max_new_tokens=6)
        eng.pool.check_leaks()
        assert eng.pool.num_free == eng.pool.capacity_blocks

    def test_eos_terminates_request(self, model):
        p = _prompts([5])[0]
        ref = _reference(model, p, max_new_tokens=8)
        eos = int(ref[5 + 2])  # third generated token
        ref_eos = _reference(model, p, max_new_tokens=8, eos_token_id=eos)
        eng = Engine(model, _config())
        req = eng.submit(p, max_new_tokens=8, eos_token_id=eos)
        eng.run_until_complete()
        assert req.finish_reason == "eos"
        np.testing.assert_array_equal(req.output_ids(), ref_eos)

    def test_stop_sequence_terminates_request(self, model):
        p = _prompts([4])[0]
        ref = _reference(model, p, max_new_tokens=8)
        stop = [int(ref[4 + 1]), int(ref[4 + 2])]  # generated bigram
        ref_stop = _reference(model, p, max_new_tokens=8,
                              stop_sequences=[stop])
        eng = Engine(model, _config())
        req = eng.submit(p, max_new_tokens=8, stop_sequences=[stop])
        eng.run_until_complete()
        assert req.finish_reason == "stop"
        assert req.generated[-2:] == stop
        np.testing.assert_array_equal(req.output_ids(), ref_stop)

    def test_single_token_request_finishes_at_prefill(self, model):
        eng = Engine(model, _config())
        [out] = eng.generate(_prompts([5]), max_new_tokens=1)
        ref = _reference(model, _prompts([5])[0], max_new_tokens=1)
        np.testing.assert_array_equal(out, ref)
        assert eng.stats()["counters"]["decode_iterations"] == 0


class TestAdmissionControl:
    def test_bounded_queue_rejects(self, model):
        eng = Engine(model, _config(max_queue_len=2))
        for _ in range(2):
            eng.submit(_prompts([3])[0], max_new_tokens=2)
        with pytest.raises(AdmissionError, match="queue full"):
            eng.submit(_prompts([3])[0], max_new_tokens=2)
        assert eng.stats()["counters"]["requests_rejected"] == 1
        eng.run_until_complete()

    def test_impossible_fit_rejected_outright(self, model):
        # capacity 3 blocks * 4 tokens = 12; this request needs 16
        eng = Engine(model, _config(num_blocks=4))
        with pytest.raises(AdmissionError, match="capacity"):
            eng.submit(_prompts([8])[0], max_new_tokens=8)

    def test_max_model_len_enforced(self, model):
        eng = Engine(model, _config())
        with pytest.raises(AdmissionError, match="max_model_len"):
            eng.submit(_prompts([4])[0],
                       max_new_tokens=eng.max_model_len)

    def test_sampling_routed_through_sampling_params(self, model):
        # generate() call-site parity: temperature/do_sample/top_k/top_p
        # route into SamplingParams (ISSUE 19) instead of being rejected;
        # invalid knobs still fail loudly AT SUBMIT, not mid-decode
        eng = Engine(model, _config())
        greedy = eng.submit(_prompts([3])[0], max_new_tokens=2,
                            temperature=0.0)
        assert greedy.sampling is None      # greedy stays off-path
        hot = eng.submit(_prompts([3])[0], max_new_tokens=2,
                         temperature=0.7, top_k=8, seed=1)
        assert hot.sampling.temperature == 0.7 and hot.sampling.top_k == 8
        ds = eng.submit(_prompts([3])[0], max_new_tokens=2,
                        do_sample=True)
        assert ds.sampling.temperature == 1.0   # reference default
        with pytest.raises(ValueError, match="top_p"):
            eng.submit(_prompts([3])[0], max_new_tokens=2,
                       do_sample=True, top_p=0.0)
        with pytest.raises(ValueError, match="temperature"):
            eng.submit(_prompts([3])[0], max_new_tokens=2,
                       sampling={"temperature": -1.0})
        eng.run_until_complete()

    def test_fcfs_completion_order(self, model):
        """One slot: requests retire strictly in arrival order."""
        eng = Engine(model, _config(max_batch_size=1))
        reqs = [eng.submit(p, max_new_tokens=3)
                for p in _prompts([3, 5, 4])]
        done = eng.run_until_complete()
        assert list(done) == [r.request_id for r in reqs]


class TestPreemption:
    def test_preempt_requeue_roundtrip_keeps_parity(self, model):
        """Pool sized so two admitted requests cannot BOTH reach full
        length: the younger is evicted mid-decode, requeued, recomputed
        — and still produces token-exact greedy output."""
        prompts = _prompts([4, 4], seed=7)
        refs = [_reference(model, p, max_new_tokens=10) for p in prompts]
        # capacity 5 blocks * 4 = 20 token-positions; each request needs
        # ceil((4+10)/4)=4 blocks at full length but only 2 to admit, so
        # both admit and later collide on the 5th block.
        eng = Engine(model, _config(max_batch_size=2, num_blocks=6))
        reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        eng.run_until_complete()
        for req, ref in zip(reqs, refs):
            np.testing.assert_array_equal(req.output_ids(), ref)
        st = eng.stats()
        assert st["counters"]["preemptions"] >= 1
        # FCFS fairness: the YOUNGER request is the victim
        assert reqs[1].preemptions >= 1 and reqs[0].preemptions == 0
        assert st["requests"][reqs[1].request_id]["preemptions"] >= 1
        eng.pool.check_leaks()

    def test_victim_is_youngest_and_head_of_queue(self, model):
        from paddle_tpu.serving.scheduler import Scheduler

        pool = BlockKVPool(2, 8, 4, 2, 4)
        sched = Scheduler(pool)
        a = Request(prompt=np.ones(4, np.int32), max_new_tokens=2)
        b = Request(prompt=np.ones(4, np.int32), max_new_tokens=2)
        sched.running = [a, b]
        assert sched.pick_victim() is b
        b.generated = [1, 2]
        sched.requeue_preempted(b)
        assert sched.waiting[0] is b
        assert b.generated == [] and b.blocks == []
        # re-admission keeps the original FCFS ordinal
        assert b.ordinal > a.ordinal


class TestMetrics:
    def test_request_timings_and_counters(self, model):
        eng = Engine(model, _config())
        reqs = [eng.submit(p, max_new_tokens=4) for p in _prompts([3, 6])]
        eng.run_until_complete()
        st = eng.stats()
        c = st["counters"]
        assert c["requests_submitted"] == 2
        assert c["requests_completed"] == 2
        assert c["prefills"] == 2
        assert c["tokens_generated"] == sum(r.num_generated for r in reqs)
        assert c["decode_iterations"] >= 3
        for req in reqs:
            t = st["requests"][req.request_id]
            assert t["ttft_s"] is not None and t["ttft_s"] >= 0
            assert t["tpot_s"] is not None and t["tpot_s"] >= 0
            assert t["queue_time_s"] >= 0
            assert t["e2e_s"] >= t["ttft_s"]
            assert t["tokens_generated"] == 4
            assert t["finish_reason"] == "length"
        g = st["gauges"]
        assert 0 < g["batch_occupancy_avg"] <= 1
        assert 0 <= g["cache_utilization_avg"] <= 1

    def test_stats_contract_for_router(self, model):
        """The load/affinity signals the fleet router places by are part
        of the ``stats()`` contract: ``pending_prefill_tokens`` (exact
        backlog token count) and ``prefix_index`` (the pool's prefix-
        cache summary in hex)."""
        eng = Engine(model, _config())
        eng.submit(_prompts([6, 9], seed=3)[0], max_new_tokens=2)
        eng.submit(_prompts([6, 9], seed=3)[1], max_new_tokens=2)
        st = eng.stats()
        assert st["queue_depth"] == 2
        assert st["pending_prefill_tokens"] == 15       # 6 + 9, untouched
        assert st["pending_prefill_tokens"] == eng.pending_prefill_tokens()
        eng.run_until_complete()
        st = eng.stats()
        assert st["pending_prefill_tokens"] == 0
        idx = st["prefix_index"]
        assert idx["block_size"] == eng.config.block_size
        assert idx["indexed_blocks"] >= 1               # prompts registered
        assert idx["cached_blocks"] >= 0
        hashes = idx["hashes"]
        assert hashes and len(hashes) == idx["indexed_blocks"]
        for h in hashes + idx["roots"]:
            int(h, 16)                                  # hex digests
            assert len(h) == 32                         # blake2b-128
        assert set(idx["roots"]) <= set(hashes)

    def test_chrome_export(self, model, tmp_path):
        import json

        eng = Engine(model, _config())
        eng.generate(_prompts([3]), max_new_tokens=3)
        path = eng.metrics.export_chrome(str(tmp_path / "trace.json"))
        events = json.load(open(path))["traceEvents"]
        names = {e["name"] for e in events}
        assert any(n.startswith("decode:") for n in names)
        assert all(e["ph"] == "X" and e["dur"] >= 0 for e in events)


class TestEndpoint:
    def test_predictor_parity_handles(self, model):
        from paddle_tpu.inference import create_serving_endpoint

        ep = create_serving_endpoint(model, _config(), max_new_tokens=4)
        assert ep.get_input_names() == ["input_0"]
        prompts = np.stack(_prompts([5, 5]))
        ep.get_input_handle("input_0").copy_from_cpu(prompts)
        outs = ep.run()
        rect = ep.get_output_handle("output_0").copy_to_cpu()
        assert rect.shape == (2, 9)
        for i, p in enumerate(prompts):
            ref = _reference(model, p, max_new_tokens=4)
            np.testing.assert_array_equal(outs[i], ref)
            np.testing.assert_array_equal(rect[i], ref)

    def test_streaming_submit_poll_result(self, model):
        from paddle_tpu.serving import Endpoint

        ep = Endpoint(model, _config(), max_new_tokens=3)
        req = ep.submit(_prompts([4])[0])
        assert ep.result(req) is None and req.state == QUEUED
        while ep.poll():
            pass
        assert req.state == FINISHED
        ref = _reference(model, _prompts([4])[0], max_new_tokens=3)
        np.testing.assert_array_equal(ep.result(req), ref)


# ---------------------------------------------------------------------------
# the continuous-batching win (slow: wall-clock-free, but extra decodes)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestThroughput:
    def test_staggered_workload_fewer_decode_iterations(self, model):
        """8 staggered requests: the engine interleaves them in one
        bucket, so TOTAL decode iterations stay well under the
        sequential sum — the continuous-batching claim, measured in
        iterations (deterministic) instead of wall clock (flaky)."""
        prompts = _prompts([3, 5, 4, 6, 3, 7, 5, 4], seed=11)
        max_new = 8
        eng = Engine(model, _config(max_batch_size=8, num_blocks=128))
        reqs = []
        for i, p in enumerate(prompts):
            reqs.append(eng.submit(p, max_new_tokens=max_new))
            eng.step()   # requests arrive WHILE others are decoding
        eng.run_until_complete()
        refs = [_reference(model, p, max_new_tokens=max_new)
                for p in prompts]
        for req, ref in zip(reqs, refs):
            np.testing.assert_array_equal(req.output_ids(), ref)
        engine_iters = eng.stats()["counters"]["decode_iterations"]
        # sequential: each request alone pays max_new - 1 decode steps
        sequential_iters = len(prompts) * (max_new - 1)
        assert engine_iters < sequential_iters, \
            (engine_iters, sequential_iters)


# ---------------------------------------------------------------------------
# Prefix cache: pool semantics (refcounts, chain hashing, LRU, CoW)
# ---------------------------------------------------------------------------

class TestPrefixCachePool:
    def _pool(self, num_blocks=8, block_size=4, **kw):
        return BlockKVPool(num_layers=2, num_blocks=num_blocks,
                           block_size=block_size, kv_heads=2, head_dim=4,
                           **kw)

    def test_free_request_unowned_is_noop(self):
        """Retire paths call free_request unconditionally — a request
        that never got blocks (queued timeout, failed prefill) must not
        blow up."""
        pool = self._pool()
        pool.free_request("never-admitted")   # no raise
        a = pool.allocate("a", 2)
        pool.free_request("a")
        pool.free_request("a")                # second call: also a no-op
        assert pool.num_free == pool.capacity_blocks
        assert 0 not in a

    def test_double_free_message_lists_owners(self):
        pool = self._pool()
        blocks = pool.allocate("alice", 1)
        pool.acquire("bob", blocks)
        with pytest.raises(ValueError, match="double free.*'carol'.*"
                                             "alice.*bob"):
            pool.free(blocks, request_id="carol")
        with pytest.raises(ValueError, match="no current owner"):
            pool.free([pool._free[-1]])

    def test_refcount_shared_block_survives_one_owner(self):
        pool = self._pool()
        blocks = pool.allocate("a", 2)
        pool.acquire("b", blocks)
        assert all(pool.refcount(b) == 2 for b in blocks)
        pool.free_request("a")
        # b still holds them: nothing came back to the free list
        assert pool.num_used == 2
        assert sorted(pool.owned_by("b")) == sorted(blocks)
        pool.free_request("b")
        assert pool.num_free == pool.capacity_blocks
        pool.check_leaks()

    def test_chain_hash_match_semantics(self):
        """Matching is chained: block i matches only when the WHOLE
        prefix through block i matches, full blocks only, stopping at
        the first divergence."""
        pool = self._pool(num_blocks=16)
        toks = np.arange(1, 13, dtype=np.int32)          # 3 full blocks
        blocks = pool.allocate("a", 3)
        pool.register_prefix("a", toks, blocks)
        assert pool.match_prefix(toks) == blocks
        assert pool.match_prefix(toks[:8]) == blocks[:2]
        assert pool.match_prefix(toks[:7]) == blocks[:1]  # partial tail
        # same 2nd block content after a DIFFERENT first block: no match
        # past the divergence (the chain encodes the whole prefix)
        other = toks.copy()
        other[0] = 99
        assert pool.match_prefix(other) == []
        pool.free_request("a")
        assert pool.match_prefix(toks) == blocks          # parked, still hot

    def test_lru_eviction_never_touches_referenced_blocks(self):
        """Under pressure the pool evicts ONLY unreferenced cached
        blocks, oldest-parked first; live requests' blocks are
        untouchable."""
        pool = self._pool(num_blocks=6)
        t1 = np.arange(1, 5, dtype=np.int32)
        t2 = np.arange(11, 15, dtype=np.int32)
        b1 = pool.allocate("a", 1)
        pool.register_prefix("a", t1, b1)
        b2 = pool.allocate("b", 1)
        pool.register_prefix("b", t2, b2)
        pool.free_request("a")        # parked first -> LRU victim
        pool.free_request("b")
        live = pool.allocate("live", 3)   # 3 truly-free blocks left
        assert pool.num_cached == 2 and pool.evictions == 0
        got = pool.allocate("live", 2)    # forces 2 evictions
        assert pool.evictions == 2
        assert set(got) == {b1[0], b2[0]}  # recycled cached blocks
        assert pool.match_prefix(t1) == [] and pool.match_prefix(t2) == []
        # live blocks never appeared as victims
        assert sorted(pool.owned_by("live")) == sorted(live + got)
        with pytest.raises(PoolExhausted):
            pool.allocate("live", 1)
        pool.free_request("live")
        pool.check_leaks()

    def test_cow_shared_and_registered_blocks(self):
        pool = self._pool()
        toks = np.arange(1, 5, dtype=np.int32)
        b = pool.allocate("a", 1)
        # exclusive + unregistered: in-place, no copy
        assert pool.ensure_writable("a", b[0]) == b[0]
        pool.register_prefix("a", toks, b)
        # registered (immutable) even while exclusively owned: copy
        nb = pool.ensure_writable("a", b[0])
        assert nb != b[0] and pool.cow_copies == 1
        assert pool.owned_by("a") == [nb]
        # the registered original stays matchable (parked in the LRU)
        assert pool.match_prefix(toks) == b
        pool.acquire("b2", pool.match_prefix(toks))
        nb2 = pool.ensure_writable("b2", b[0])   # shared again: copy
        assert nb2 not in (b[0], nb) and pool.cow_copies == 2
        pool.free_request("a")
        pool.free_request("b2")
        pool.check_leaks()

    def test_acquire_revives_parked_block(self):
        pool = self._pool()
        toks = np.arange(1, 5, dtype=np.int32)
        b = pool.allocate("a", 1)
        pool.register_prefix("a", toks, b)
        pool.free_request("a")
        assert pool.num_cached == 1
        pool.acquire("b", b)
        assert pool.num_cached == 0 and pool.refcount(b[0]) == 1
        pool.free_request("b")
        pool.check_leaks()

    def test_disabled_cache_never_matches_or_parks(self):
        pool = self._pool(enable_prefix_cache=False)
        toks = np.arange(1, 5, dtype=np.int32)
        b = pool.allocate("a", 1)
        assert pool.register_prefix("a", toks, b) == 0
        assert pool.match_prefix(toks) == []
        pool.free_request("a")
        assert pool.num_cached == 0
        assert pool.num_free == pool.capacity_blocks


# ---------------------------------------------------------------------------
# Prefix cache + chunked prefill: engine-level done bar
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_cache_on_off_token_identical(self, model):
        """ISSUE 5 parity obligation: greedy output is token-identical
        with prefix cache + chunked prefill enabled vs disabled, and
        both match sequential generate()."""
        shared = _prompts([16], seed=21)[0]
        tails = _prompts([3, 5, 2], seed=22)
        prompts = [np.concatenate([shared, t]) for t in tails]
        refs = [_reference(model, p, max_new_tokens=6) for p in prompts]
        outs = {}
        for enable in (False, True):
            eng = Engine(model, _config(chunk_tokens=8,
                                        enable_prefix_cache=enable))
            outs[enable] = []
            for p in prompts:       # sequential: later ones hit the cache
                req = eng.submit(p, max_new_tokens=6)
                eng.run_until_complete()
                outs[enable].append(req.output_ids())
            eng.pool.check_leaks()
            if enable:
                c = eng.metrics.as_dict()["counters"]
                assert c["prefix_cache_hits"] == 2
                assert c["prefix_cache_misses"] == 1
        for off, on, ref in zip(outs[False], outs[True], refs):
            np.testing.assert_array_equal(off, on)
            np.testing.assert_array_equal(on, ref)

    def test_full_prompt_hit_recomputes_last_token(self, model):
        """Submitting the SAME prompt twice: the second admission may
        reuse every full block, but must still recompute >= 1 token to
        produce first-token logits — via a copy-on-write block, so the
        cached original is never mutated."""
        p = _prompts([8], seed=23)[0]      # exact multiple of block_size
        ref = _reference(model, p, max_new_tokens=5)
        eng = Engine(model, _config(chunk_tokens=8))
        for _ in range(2):
            req = eng.submit(p, max_new_tokens=5)
            eng.run_until_complete()
            np.testing.assert_array_equal(req.output_ids(), ref)
        assert eng.metrics.prefix_cache_hits == 1
        assert eng.pool.cow_copies >= 1
        # the second request prefilled ONE 1-token chunk, not the prompt
        assert req.cached_tokens == p.size - 1
        eng.pool.check_leaks()

    def test_constant_prefill_programs_across_lengths(self):
        """ISSUE 5 acceptance: >= 4 distinct prompt lengths, ONE
        compiled prefill program (the fixed-chunk shape), measured via
        the compile tracker — the bucketed prefill would have compiled
        one per length bucket."""
        paddle.seed(0)
        fresh = LlamaForCausalLM(LlamaConfig.tiny())
        fresh.eval()
        eng = Engine(fresh, _config(chunk_tokens=4))
        prompts = _prompts([3, 7, 11, 14, 6], seed=24)
        refs = [_reference(fresh, p, max_new_tokens=4) for p in prompts]
        outs = eng.generate(prompts, max_new_tokens=4)
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        assert eng._prefill_step.compiles == 1, \
            eng._prefill_step.compiles
        assert eng.prefill_cache_size() == 1
        assert eng._prefill_step.retraces == 0
        # multi-chunk accounting: ceil(L/4) chunks per prompt
        assert eng.metrics.prefill_chunks == sum(
            -(-p.size // 4) for p in prompts)

    def test_eviction_under_pressure_keeps_parity(self, model):
        """Tiny pool + repeated prompts: LRU evictions and preemptions
        churn the cache, yet every output stays token-exact and no
        live-referenced block is ever handed out twice (the leak check
        would catch a double-owned block)."""
        prompts = _prompts([4, 4, 8, 4], seed=7)
        prompts.append(prompts[0].copy())    # full-hit after churn
        refs = [_reference(model, p, max_new_tokens=10) for p in prompts]
        eng = Engine(model, _config(max_batch_size=3, num_blocks=7,
                                    chunk_tokens=8))
        outs = eng.generate(prompts, max_new_tokens=10)
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        assert eng.pool.evictions > 0        # pressure was real
        assert eng.metrics.preempted > 0
        eng.pool.check_leaks()
        assert eng.pool.num_free == eng.pool.capacity_blocks

    def test_preempted_request_reuses_its_own_prefix(self, model):
        """A preempted request's registered prompt blocks survive in
        the LRU; its re-admission is a prefix-cache hit and the rerun
        stays token-exact (recompute mode + cache reuse compose)."""
        prompts = _prompts([8, 8], seed=25)
        refs = [_reference(model, p, max_new_tokens=10) for p in prompts]
        # capacity 6: both prefill (4 blocks), decode growth preempts
        # the younger request, and the survivor finishes with 5 blocks —
        # evicting the victim's parked TAIL but leaving its chain head
        # for the re-admission to hit (leaf-first eviction order)
        eng = Engine(model, _config(max_batch_size=2, num_blocks=7,
                                    chunk_tokens=8))
        outs = eng.generate(prompts, max_new_tokens=10)
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        assert eng.metrics.preempted > 0
        assert eng.metrics.prefix_cache_hits > 0
        eng.pool.check_leaks()

    def test_long_prompt_interleaves_with_decode(self, model):
        """Sarathi-style budget: while a long prompt prefills chunk by
        chunk, an already-running request keeps producing tokens every
        iteration (no prefill stall), and both finish token-exact."""
        short, long_ = _prompts([4, 40], seed=26)
        refs = [_reference(model, p, max_new_tokens=8)
                for p in (short, long_)]
        eng = Engine(model, _config(chunk_tokens=8))
        r_short = eng.submit(short, max_new_tokens=8)
        eng.step()                      # short is admitted + running
        gen_before = r_short.num_generated
        r_long = eng.submit(long_, max_new_tokens=8)
        steps = 0
        while r_long.state != FINISHED and r_short.state != FINISHED:
            eng.step()
            steps += 1
        # the short request advanced during the long prompt's prefill
        assert r_short.num_generated > gen_before
        eng.run_until_complete()
        np.testing.assert_array_equal(r_short.output_ids(), refs[0])
        np.testing.assert_array_equal(r_long.output_ids(), refs[1])
        assert r_long.prefill_chunks == 5    # ceil(40 / 8)
        eng.pool.check_leaks()
