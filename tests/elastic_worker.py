"""Elastic-training worker: registers with ElasticManager over TCPStore,
heartbeats, and trains a tiny model with per-step checkpoints until killed.
(The reference kills real trainer subprocesses in its elastic tests —
SURVEY.md §4.)"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.store import TCPStore

    host, port = os.environ["ELASTIC_STORE"].rsplit(":", 1)
    ckpt = os.environ["ELASTIC_CKPT"]
    store = TCPStore(host=host, port=int(port), is_master=False,
                     world_size=2)
    mgr = ElasticManager(store, node_id=os.environ["ELASTIC_NODE"],
                         np_range=(1, 2), heartbeat_interval=0.2,
                         lease_ttl=1.5)
    mgr.register()

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 2, (8,)).astype(np.int32))
    step = 0
    print("worker started", flush=True)
    while True:  # until killed
        loss = nn.functional.cross_entropy(net(x), y)
        loss.backward()
        for p in net.parameters():
            if p.grad is not None:
                p.set_value(p._value - 0.1 * p.grad._value)
        net.clear_gradients()
        step += 1
        state = {"step": step, "loss": float(loss.numpy()),
                 "weights": net.state_dict()}
        paddle.save(state, ckpt + ".tmp")
        os.replace(ckpt + ".tmp", ckpt)
        store.set("worker_step", str(step))
        if step == 1:
            print("first checkpoint written", flush=True)


if __name__ == "__main__":
    main()
