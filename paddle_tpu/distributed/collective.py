"""Collective communication API.

Reference surface: python/paddle/distributed/collective.py (all_reduce:592,
all_gather:814, alltoall:1738, send:1840, recv:1903, new_group:325) backed by
ProcessGroupNCCL.  TPU-native semantics:

- Inside a shard_map/SPMD trace (a mesh axis name is in scope) each call
  lowers to the XLA collective (psum / all_gather / all_to_all / ppermute)
  over ICI — this is the performance path the compiler schedules.
- Eagerly in the single-controller model there is one process that owns all
  chips: cross-"rank" collectives over a group of size 1 are identity, and
  send/recv have no peer — they raise, directing users to the SPMD path.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .env import get_rank, get_world_size
from .mesh import _AxisGroup, get_mesh


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


Group = _AxisGroup

_GROUPS = {}


def _axis_in_scope(axis_name) -> bool:
    """True when called under shard_map with this axis bound."""
    if axis_name is None:
        return False
    try:
        jax.lax.axis_index(axis_name)
        return True
    except (NameError, Exception):
        return False


def new_group(ranks=None, backend=None, timeout=None):
    """Create a group.  In the SPMD model a group is a mesh-axis view; a
    ranks list matching a whole axis maps onto it, anything else gets a
    trivial group (single-controller: every collective is compiled)."""
    mesh = get_mesh()
    nranks = len(ranks) if ranks else get_world_size()
    axis = None
    if mesh is not None:
        for name, size in mesh.shape.items():
            if size == nranks:
                axis = name
                break
    g = _AxisGroup(axis, nranks, 0, ranks or range(nranks))
    _GROUPS[g.id] = g
    return g


def get_group(gid=0):
    return _GROUPS.get(gid)


def _group_axis(group):
    if group is None:
        mesh = get_mesh()
        if mesh is not None and len(mesh.shape) == 1:
            return list(mesh.shape)[0]
        return None
    return group.axis_name


def _world_mesh():
    """1-device-per-process mesh for eager cross-process collectives.

    Using one device per process (the first of each) keeps the global
    array's leading dim == process_count divisible regardless of how many
    chips each host owns; every process still participates in the compiled
    collective, so the reduction is correct on multi-chip hosts too."""
    import numpy as np
    from jax.sharding import Mesh

    per_process = {}
    for d in jax.devices():
        per_process.setdefault(d.process_index, d)
    devs = [per_process[p] for p in sorted(per_process)]
    return Mesh(np.asarray(devs), ("world",))


_CROSS_FNS = {}


def _cross_process_all_reduce(value, op):
    """Eager all-reduce across OS processes: every process contributes its
    local value to one compiled collective over the global mesh (the
    multi-controller analog of the reference ProcessGroup AllReduce task,
    ProcessGroup.h:53).  All processes must call this collectively."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = jax.process_count()
    mesh = _world_mesh()
    dev = jax.local_devices()[0]
    sharding = NamedSharding(mesh, P("world"))
    garr = jax.make_array_from_single_device_arrays(
        (n,) + value.shape, sharding,
        [jax.device_put(value[None], dev)])
    key = (op, value.shape, str(value.dtype))
    fn = _CROSS_FNS.get(key)
    if fn is None:
        def reduce_fn(x):
            if op == ReduceOp.SUM:
                return jnp.sum(x, axis=0)
            if op == ReduceOp.MAX:
                return jnp.max(x, axis=0)
            if op == ReduceOp.MIN:
                return jnp.min(x, axis=0)
            if op == ReduceOp.AVG:
                return jnp.mean(x, axis=0)
            if op == ReduceOp.PROD:
                return jnp.prod(x, axis=0)
            raise ValueError(op)

        fn = jax.jit(reduce_fn,
                     out_shardings=NamedSharding(mesh, P()))
        _CROSS_FNS[key] = fn
    out = fn(garr)
    return out.addressable_shards[0].data


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _group_axis(group)
    if _axis_in_scope(axis):
        def _ar(v):
            if op == ReduceOp.SUM:
                return jax.lax.psum(v, axis)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(v, axis)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(v, axis)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(v, axis)
            if op == ReduceOp.PROD:
                return jnp.exp(jax.lax.psum(jnp.log(v), axis))
            raise ValueError(op)
        out = apply("all_reduce", _ar, tensor)
        tensor._rebind(out)
        return tensor
    if jax.process_count() > 1 and group is None:
        # eager cross-process collective (multi-controller runtime)
        tensor.set_value(_cross_process_all_reduce(tensor._value, op))
        return tensor
    # eager single-controller: group of compiled ranks not in scope → identity
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        out = apply("all_gather",
                    lambda v: jax.lax.all_gather(v, ax, tiled=False), tensor)
        n = out.shape[0]
        from ..ops.manipulation import unbind

        parts = unbind(out, 0)
        if isinstance(tensor_list, list):
            tensor_list.clear()
            tensor_list.extend(parts)
        return parts
    if isinstance(tensor_list, list):
        tensor_list.clear()
        tensor_list.append(tensor)
    return [tensor]


def all_gather_object(object_list, obj, group=None):
    object_list.clear()
    object_list.append(obj)
    return object_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        src = tensor_list if tensor_list is not None else tensor

        def _rs(v):
            return jax.lax.psum_scatter(v, ax, scatter_dimension=0, tiled=True)
        if isinstance(src, (list, tuple)):
            from ..ops.manipulation import concat

            src = concat(list(src), axis=0)
        out = apply("reduce_scatter", _rs, src)
        tensor._rebind(out)
        return tensor
    if tensor_list is not None and isinstance(tensor_list, (list, tuple)):
        tensor._rebind(tensor_list[0])
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        from ..ops.manipulation import concat, unbind, stack

        x = stack(list(in_tensor_list), axis=0) \
            if isinstance(in_tensor_list, (list, tuple)) else in_tensor_list

        def _a2a(v):
            return jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0,
                                      tiled=False)
        out = apply("alltoall", _a2a, x)
        parts = unbind(out, 0)
        if isinstance(out_tensor_list, list):
            out_tensor_list.clear()
            out_tensor_list.extend(parts)
        return parts
    if isinstance(out_tensor_list, list):
        out_tensor_list.clear()
        out_tensor_list.extend(list(in_tensor_list))
    return list(in_tensor_list)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        def _a2a(v):
            return jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0,
                                      tiled=True)
        out = apply("alltoall_single", _a2a, in_tensor)
        if out_tensor is not None:
            out_tensor._rebind(out)
            return out_tensor
        return out
    if out_tensor is not None:
        out_tensor._rebind(in_tensor)
        return out_tensor
    return in_tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        def _bc(v):
            # select src's value on every member of the axis
            full = jax.lax.all_gather(v, ax)
            return full[src]
        out = apply("broadcast", _bc, tensor)
        tensor._rebind(out)
        return tensor
    if jax.process_count() > 1 and group is None:
        from .env import get_rank

        v = tensor._value
        contrib = v if get_rank() == src else jnp.zeros_like(v)
        tensor.set_value(_cross_process_all_reduce(contrib, ReduceOp.SUM))
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # SPMD: reduce == all_reduce (every shard holds the result)
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        from ..ops.manipulation import stack

        x = stack(list(tensor_list), axis=0)

        def _sc(v):
            idx = jax.lax.axis_index(ax)
            return jnp.take(v, idx, axis=0)
        out = apply("scatter", _sc, x)
        tensor._rebind(out)
        return tensor
    if tensor_list:
        tensor._rebind(tensor_list[src])
    return tensor


def send(tensor, dst=0, group=None, sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        # point-to-point on a ring: collective_permute
        def _send(v):
            n = jax.lax.axis_size(ax)
            perm = [(i, dst) for i in range(n)]
            return jax.lax.ppermute(v, ax, perm)
        return apply("send", _send, tensor)
    raise RuntimeError(
        "eager send/recv has no peer process in the single-controller model; "
        "express P2P inside shard_map (ppermute) or use the pipeline API")


def recv(tensor, src=0, group=None, sync_op=True):
    ax = _group_axis(group)
    if _axis_in_scope(ax):
        def _recv(v):
            n = jax.lax.axis_size(ax)
            perm = [(src, i) for i in range(n)]
            return jax.lax.ppermute(v, ax, perm)
        out = apply("recv", _recv, tensor)
        tensor._rebind(out)
        return tensor
    raise RuntimeError(
        "eager send/recv has no peer process in the single-controller model; "
        "express P2P inside shard_map (ppermute) or use the pipeline API")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


class _DoneTask:
    def wait(self):
        return True

    def is_completed(self):
        return True


def barrier(group=None):
    """Host-level barrier: single controller → trivially passed; multi-host
    uses the TCPStore barrier in distributed.launch."""
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    return _DoneTask()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        try:
            tensor._value.block_until_ready()
        except Exception:
            pass
    return None


def stream_wait(*a, **k):
    return None


class ParallelMode:
    """Parallelism kind enum (reference:
    python/paddle/distributed/parallel.py ParallelMode)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel linear/embedding in one call (reference:
    distributed/collective.py split — builds the partitioned weight and
    the collective).  TPU-native: delegates to the GSPMD parallel layers
    (parallel_layers.py), whose shardings compile to the same collectives
    the reference inserts by hand."""
    from .parallel_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError(f"split supports linear/embedding, got {operation}")
    has_bias = bias_attr is not False
    if axis == 0:
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  has_bias=has_bias,
                                  input_is_parallel=False)
    elif axis == 1:
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=has_bias,
                                     gather_output=gather_out)
    else:
        raise ValueError("axis must be 0 (row) or 1 (column)")
    return layer(x)


# host-side barrier family over the TCPStore (reference: gloo_* in
# python/paddle/distributed/parallel.py — CPU-only barriers via gloo;
# the store is our gloo-position component)
_GLOO_STORE = None


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    global _GLOO_STORE
    from .store import TCPStore

    host, port = server_endpoint.rsplit(":", 1)
    _GLOO_STORE = TCPStore(host, int(port), is_master=(rank_id == 0))
    _GLOO_STORE.add("gloo/init", 1)
    import time

    # monotonic, not wall clock (hazard H111): an NTP step mid-
    # rendezvous would fire this timeout early or stretch it
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if _GLOO_STORE.add("gloo/init", 0) >= rank_num:
            return
        time.sleep(0.01)
    raise TimeoutError("gloo_init_parallel_env rendezvous timed out")


_gloo_barrier_round = [0]


def gloo_barrier():
    if _GLOO_STORE is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    _gloo_barrier_round[0] += 1
    key = f"gloo/barrier/{_gloo_barrier_round[0]}"
    world = _GLOO_STORE.add("gloo/init", 0)
    _GLOO_STORE.add(key, 1)
    import time

    deadline = time.monotonic() + 30      # H111: never the wall clock
    while time.monotonic() < deadline:
        if _GLOO_STORE.add(key, 0) >= world:
            return
        time.sleep(0.01)
    raise TimeoutError("gloo_barrier timed out")


def gloo_release():
    global _GLOO_STORE
    _GLOO_STORE = None
