"""Mixture-of-Experts with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:233
MoELayer + gates (naive/gshard/switch, moe/gate/*.py) + global_scatter/
global_gather alltoall ops (paddle/fluid/operators/collective/
global_scatter_op.*).

TPU-native (GShard recipe — XLA hates dynamic token counts, so routing is
capacity-padded with static shapes): expert weights are stacked with a
leading expert axis sharded over the mesh axis "ep"; dispatch/combine are
einsums against a [tokens, E, C] one-hot, and GSPMD lowers the expert-axis
resharding to the same all-to-all the reference codes by hand.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from .sharding import mark_sharding


def _top2_gating(logits, capacity, second_policy="all"):
    """GShard top-2 gating → (combine [T,E,C], dispatch [T,E,C], aux_loss)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    g1_idx = jnp.argmax(probs, axis=-1)
    g1_prob = jnp.max(probs, axis=-1)
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(g1_idx, E))
    g2_idx = jnp.argmax(probs_wo1, axis=-1)
    g2_prob = jnp.max(probs_wo1, axis=-1)

    # aux load-balance loss (GShard eq.4): E * mean(me * ce)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(g1_idx, E), axis=0)
    aux = jnp.sum(me * ce) * E

    mask1 = jax.nn.one_hot(g1_idx, E)
    mask2 = jax.nn.one_hot(g2_idx, E)
    # positions within each expert (cumsum over tokens)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - 1.0
    mask1 = mask1 * (pos1 < capacity)
    pos2 = (jnp.cumsum(mask2, axis=0) + jnp.sum(mask1, axis=0,
                                                keepdims=True)) * mask2 - 1.0
    mask2 = mask2 * (pos2 < capacity)

    denom = g1_prob + g2_prob + 1e-9
    w1 = (g1_prob / denom) * jnp.sum(mask1, axis=1)
    w2 = (g2_prob / denom) * jnp.sum(mask2, axis=1)

    p1 = jnp.einsum("te,te->t", pos1, mask1).astype(jnp.int32)
    p2 = jnp.einsum("te,te->t", pos2, mask2).astype(jnp.int32)
    c1 = jax.nn.one_hot(jnp.clip(p1, 0, capacity - 1), capacity)
    c2 = jax.nn.one_hot(jnp.clip(p2, 0, capacity - 1), capacity)
    combine = (w1[:, None, None] * mask1[:, :, None] * c1[:, None, :]
               + w2[:, None, None] * mask2[:, :, None] * c2[:, None, :])
    dispatch = combine > 0.0
    return combine, dispatch, aux


def _top1_gating(logits, capacity, jitter_eps=0.0):
    """Switch-transformer gating."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    g_idx = jnp.argmax(probs, axis=-1)
    g_prob = jnp.max(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(g_idx, E), axis=0)
    aux = jnp.sum(me * ce) * E
    mask = jax.nn.one_hot(g_idx, E)
    pos = jnp.cumsum(mask, axis=0) * mask - 1.0
    mask = mask * (pos < capacity)
    p = jnp.einsum("te,te->t", pos, mask).astype(jnp.int32)
    c = jax.nn.one_hot(jnp.clip(p, 0, capacity - 1), capacity)
    combine = g_prob[:, None, None] * mask[:, :, None] * c[:, None, :]
    dispatch = combine > 0.0
    return combine, dispatch, aux


class ExpertMLP(nn.Layer):
    """Stacked expert FFNs: weights [E, ...] sharded on the ep axis."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.activation = activation
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden],
            default_initializer=I.XavierUniform())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            default_initializer=I.XavierUniform())
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        for p in (self.w1, self.b1, self.w2, self.b2):
            mark_sharding(p, PartitionSpec("ep"))

    def forward(self, x):
        """x: [E, C, d_model] → [E, C, d_model]."""
        def _expert(v, w1, b1, w2, b2):
            h = jnp.einsum("ecd,edm->ecm", v, w1) + b1
            h = jax.nn.gelu(h) if self.activation == "gelu" else \
                jax.nn.silu(h) if self.activation in ("silu", "swish") else \
                jax.nn.relu(h)
            return jnp.einsum("ecm,emd->ecd", h, w2) + b2
        return apply("expert_mlp", _expert, x, self.w1, self.b1, self.w2,
                     self.b2)


class MoELayer(nn.Layer):
    """Reference MoELayer analog (moe_layer.py:233)."""

    def __init__(self, d_model, d_hidden=None, num_experts=8, top_k=2,
                 capacity_factor=1.25, gate: str = "gshard", experts=None,
                 ep_group=None, recompute_interval=0, activation="gelu",
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate_type = gate if isinstance(gate, str) else "gshard"
        self.gate = nn.Linear(d_model, num_experts, bias_attr=False)
        self.experts = experts if experts is not None else ExpertMLP(
            num_experts, d_model, d_hidden or 4 * d_model, activation)
        self.aux_loss = None

    def forward(self, x):
        """x: [B, T, d] (or [T, d]).  Returns same shape; aux (load-balance)
        loss stored on self.aux_loss."""
        orig_shape = x.shape
        from ..ops.manipulation import reshape

        flat = reshape(x, [-1, self.d_model])
        T = flat.shape[0]
        capacity = max(int(self.capacity_factor * T * self.top_k
                           / self.num_experts), 1)
        logits = self.gate(flat)

        gate_fn = _top2_gating if (self.gate_type == "gshard"
                                   and self.top_k >= 2) else _top1_gating

        def _route(lg):
            combine, dispatch, aux = gate_fn(lg.astype(jnp.float32), capacity)
            return combine, dispatch.astype(lg.dtype), aux
        combine, dispatch, aux = apply("moe_gate", _route, logits)
        self.aux_loss = aux

        def _dispatch(v, d):
            return jnp.einsum("tec,td->ecd", d.astype(v.dtype), v)
        expert_in = apply("moe_dispatch", _dispatch, flat, dispatch)
        expert_out = self.experts(expert_in)

        def _combine(c, eo):
            return jnp.einsum("tec,ecd->td", c.astype(eo.dtype), eo)
        out = apply("moe_combine", _combine, combine, expert_out)
        return reshape(out, orig_shape)


class MoEMLP(MoELayer):
    pass
