"""paddle.distributed — TPU-native distributed training.

The reference builds distribution from NCCL process groups + per-rank OS
processes (SURVEY.md §2.2).  Here the first-class citizens are the device
Mesh (jax.sharding) and XLA collectives over ICI; ProcessGroup/collective
APIs are kept as the compatibility surface and the fleet API drives GSPMD
sharding instead of manual comm scheduling.
"""
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .mesh import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, fleet_mesh, get_mesh,
    init_mesh, ProcessMesh,
)
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, barrier, broadcast, get_group,
    gloo_barrier, gloo_init_parallel_env, gloo_release, irecv, isend,
    new_group, recv, reduce, reduce_scatter, scatter, send, split,
    wait, Group, ParallelMode, ReduceOp,
)
from .parallel import init_parallel_env  # noqa: F401
from . import bootstrap  # noqa: F401
from .bootstrap import (ClusterInfo, ProcessContext,  # noqa: F401
                        emulated_process_context, initialize_cluster,
                        spawn_local)
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .sharding import shard_tensor, shard_op, reshard  # noqa: F401
from .sharding import (SpecLayout, llama_param_role,  # noqa: F401
                       llama_param_specs)
from .moe import ExpertMLP, MoELayer  # noqa: F401
from .pipeline import (LayerDesc, PipelineLayer, PipelineParallel,  # noqa: F401
                       SharedLayerDesc, gpipe_spmd, pipeline_1f1b,
                       Compiled1F1BProgram, functional_call)
from .recompute import recompute, recompute_sequential  # noqa: F401
from .heter import ProcessGroupHeter  # noqa: F401
from . import utils  # noqa: F401
from .utils import global_gather, global_scatter  # noqa: F401
from .store import TCPStore  # noqa: F401
from ..kernels.ring_attention import ring_attention  # noqa: F401
from ..kernels.ulysses_attention import ulysses_attention  # noqa: F401
from . import auto_parallel  # noqa: F401
from . import passes  # noqa: F401
from . import sharding as sharding_module  # noqa: F401
from .sharding import (group_sharded_parallel,  # noqa: F401
                       save_group_sharded_model)
from .entry_attr import (CountFilterEntry, ProbabilityEntry,  # noqa: F401
                         ShowClickEntry)
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401
from . import planner  # noqa: F401
from .planner import CostModel, Planner  # noqa: F401
from . import launch  # noqa: F401
from .fleet_executor import FleetExecutor, TaskNode  # noqa: F401
from . import executor  # noqa: F401
from .executor import (MeshExecutor, active_mesh,  # noqa: F401
                       active_mesh_axes, as_executor, current_executor,
                       default_shardplan_mesh)


def is_initialized():
    from .mesh import _GLOBAL_MESH

    return _GLOBAL_MESH is not None


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Multi-process launch helper (reference: distributed/spawn.py).  On TPU
    a single process drives all local chips via SPMD, so spawn degenerates to
    a direct call for nprocs<=1 and raises otherwise."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    raise NotImplementedError(
        "multi-process spawn is not the TPU execution model; one process "
        "drives all local chips via the mesh (use paddle_tpu.distributed.launch "
        "for multi-host)")
