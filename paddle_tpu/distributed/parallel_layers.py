"""Tensor-parallel layers.

Reference: Megatron-style mp_layers
(/root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py:30 VocabParallelEmbedding, :97
ColumnParallelLinear, :170 RowParallelLinear, :249 ParallelCrossEntropy) —
implemented there with c_identity/c_allreduce/c_embedding collective ops.

TPU-native: each layer holds the FULL logical weight annotated with a mesh
sharding (column → PartitionSpec(None, "mp"); row → PartitionSpec("mp",
None); vocab embedding → PartitionSpec("mp", None)).  Under jit, GSPMD
partitions the matmuls and inserts the same all-reduces the reference codes
by hand — scheduled with overlap by XLA.  Eagerly on one chip they behave as
dense layers (degree-1 groups), matching the reference's single-rank path.
"""
from __future__ import annotations

import contextlib
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from .mesh import get_hybrid_communicate_group, get_mesh
from .sharding import mark_sharding, shard_tensor


def _mp_size():
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size()
    mesh = get_mesh()
    return mesh.shape.get("mp", 1) if mesh is not None else 1


# ---------------------------------------------------------------------------
# Manual-collective mode (inside shard_map, e.g. the compiled 1F1B pipeline)
#
# Under GSPMD jit the layers below hold GLOBAL weights with sharding
# annotations and XLA inserts the collectives.  Inside shard_map (the
# compiled pipeline schedule runs per-device code) weights arrive as LOCAL
# mp shards and the collectives must be explicit — the same split the
# reference makes between its GSPMD-less manual layers (c_identity /
# c_allreduce autograd ops, mp_layers.py:30) and auto parallel.  The
# pipeline builder activates this mode around stage tracing.
#
# Gradient rule (Megatron f/g pair): a plain lax.psum is NOT its own
# correct vjp under shard_map check_vma=False — the transpose overcounts
# by the axis size.  Hence identity-fwd/psum-bwd (column input) and
# psum-fwd/identity-bwd (row output) custom-vjp ops, verified exact
# against dense math in tests/test_distributed.py.
# ---------------------------------------------------------------------------

_MANUAL_AXES: dict = {}


@contextlib.contextmanager
def manual_collective_axes(axis_sizes: dict):
    """Activate manual-collective mode for the given {axis_name: size}
    mesh axes (tracing-time switch; shard_map traces synchronously)."""
    global _MANUAL_AXES
    prev = _MANUAL_AXES
    _MANUAL_AXES = dict(axis_sizes)
    try:
        yield
    finally:
        _MANUAL_AXES = prev


def manual_axis(name: str):
    """(axis_name, size) if manual mode is active for `name` with degree
    > 1, else (None, 1)."""
    size = _MANUAL_AXES.get(name, 1)
    return (name, size) if size > 1 else (None, 1)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_allreduce(x, axis):
    """psum forward, identity backward (reference c_allreduce_sum op in
    RowParallelLinear.forward: mp_layers.py:170)."""
    return jax.lax.psum(x, axis)


def _ar_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _ar_bwd(axis, _, ct):
    return (ct,)


mp_allreduce.defvjp(_ar_fwd, _ar_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_identity(x, axis):
    """identity forward, psum backward (reference c_identity op at
    ColumnParallelLinear's input: mp_layers.py:97)."""
    return x


def _id_fwd(x, axis):
    return x, None


def _id_bwd(axis, _, ct):
    return (jax.lax.psum(ct, axis),)


mp_identity.defvjp(_id_fwd, _id_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_all_gather(x, axis):
    """Concat-gather along the LAST dim forward; slice backward
    (ColumnParallelLinear gather_output=True: mp_layers.py c_concat)."""
    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


def _ag_fwd(x, axis):
    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True), \
        x.shape[-1]


def _ag_bwd(axis, local_width, ct):
    rank = jax.lax.axis_index(axis)
    start = rank * local_width
    return (jax.lax.dynamic_slice_in_dim(ct, start, local_width,
                                         axis=ct.ndim - 1),)


mp_all_gather.defvjp(_ag_fwd, _ag_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_scatter(x, axis):
    """Slice this rank's chunk of the LAST dim forward; concat-gather
    backward (Megatron scatter: each rank's input-grad chunk must be
    re-assembled into the full replicated cotangent — a bare
    dynamic_slice transpose would zero-pad instead, leaving upstream
    grads rank-inconsistent)."""
    size = jax.lax.psum(1, axis)
    local = x.shape[-1] // size
    rank = jax.lax.axis_index(axis)
    return jax.lax.dynamic_slice_in_dim(x, rank * local, local,
                                        axis=x.ndim - 1)


def _sc_fwd(x, axis):
    return mp_scatter(x, axis), None


def _sc_bwd(axis, _, ct):
    return (jax.lax.all_gather(ct, axis, axis=ct.ndim - 1, tiled=True),)


mp_scatter.defvjp(_sc_fwd, _sc_bwd)


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mp_size()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, PartitionSpec(None, "mp"))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            mark_sharding(self.bias, PartitionSpec("mp"))
        else:
            self.bias = None

    def forward(self, x):
        axis, _ = manual_axis("mp")
        if axis is not None:
            # shard_map mode: weight/bias are LOCAL mp shards.  Identity
            # fwd / psum bwd at the input (each rank contributes its
            # shard's partial input-grad), local matmul, optional gather.
            xi = apply("mp_identity", lambda v: mp_identity(v, axis), x)
            out = F.linear(xi, self.weight, self.bias)
            if self.gather_output:
                out = apply("mp_all_gather",
                            lambda v: mp_all_gather(v, axis), out)
            return out
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output and get_mesh() is not None and \
                "mp" in get_mesh().shape:
            nd = out.ndim
            out = shard_tensor(out, placements=[None] * (nd - 1) + ["mp"])
        return out


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mp_size()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, PartitionSpec("mp", None))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        axis, _ = manual_axis("mp")
        if axis is not None:
            # shard_map mode: local matmul on the row shard, psum-fwd/
            # identity-bwd allreduce, bias added ONCE after the reduce
            # (reference mp_layers.py:170 adds bias post-c_allreduce)
            def row(xv, wv):
                if xv.shape[-1] != wv.shape[0]:
                    # full (non-parallel) input: scatter this rank's
                    # slice (all-gather backward, not zero-pad)
                    xv = mp_scatter(xv, axis)
                return mp_allreduce(xv @ wv, axis)

            out = apply("row_parallel_linear", row, x, self.weight)
            if self.bias is not None:
                out = out + self.bias
            return out
        # contraction dim sharded on mp → GSPMD inserts the all-reduce the
        # reference codes as c_allreduce_sum after the local matmul
        out = F.linear(x, self.weight, self.bias)
        return out


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.world_size = _mp_size()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        mark_sharding(self.weight, PartitionSpec("mp", None))

    def forward(self, x):
        axis, _ = manual_axis("mp")
        if axis is not None:
            # shard_map mode: masked local-range lookup + allreduce — the
            # reference's c_embedding kernel (indices offset by
            # vocab_start, out-of-range rows zeroed, then allreduce)
            def emb(idx, wv):
                vloc = wv.shape[0]
                rank = jax.lax.axis_index(axis)
                loc = idx.astype(jnp.int32) - rank * vloc
                mask = (loc >= 0) & (loc < vloc)
                e = jnp.take(wv, jnp.clip(loc, 0, vloc - 1), axis=0)
                e = jnp.where(mask[..., None], e, 0)
                return mp_allreduce(e, axis)

            return apply("vocab_parallel_embedding", emb, x, self.weight)
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax cross entropy (reference mp_layers.py:249 →
    c_softmax_with_cross_entropy op).  With logits sharded on the vocab axis,
    GSPMD partitions log_softmax's reduction into the same max/sum
    all-reduce pattern the hand-written kernel uses."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class RNGStatesTracker:
    """Per-region RNG isolation (reference: parallel_layers/random.py:32) —
    distinct named seeds for 'global' vs 'local' (per-mp-rank) dropout."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        import jax as _jax

        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        self.states_[name] = _jax.random.PRNGKey(seed)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        from ..ops import random as rnd

        @contextlib.contextmanager
        def ctx():
            if name not in self.states_:
                raise ValueError(f"unknown rng region {name}")
            gen = rnd.default_generator()
            saved = gen._key
            gen._key = self.states_[name]
            try:
                yield
            finally:
                self.states_[name] = gen._key
                gen._key = saved
        return ctx()


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    from ..ops import random as rnd

    seed = seed or (1024 + pyrandom.randint(0, 10000))
    global _RNG_TRACKER
    _RNG_TRACKER = RNGStatesTracker()
    rnd.seed(seed)
    _RNG_TRACKER.add("model_parallel_rng", seed + 1)
    _RNG_TRACKER.add("global_seed", seed + 2)
