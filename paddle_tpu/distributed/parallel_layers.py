"""Tensor-parallel layers.

Reference: Megatron-style mp_layers
(/root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py:30 VocabParallelEmbedding, :97
ColumnParallelLinear, :170 RowParallelLinear, :249 ParallelCrossEntropy) —
implemented there with c_identity/c_allreduce/c_embedding collective ops.

TPU-native: each layer holds the FULL logical weight annotated with a mesh
sharding (column → PartitionSpec(None, "mp"); row → PartitionSpec("mp",
None); vocab embedding → PartitionSpec("mp", None)).  Under jit, GSPMD
partitions the matmuls and inserts the same all-reduces the reference codes
by hand — scheduled with overlap by XLA.  Eagerly on one chip they behave as
dense layers (degree-1 groups), matching the reference's single-rank path.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from .mesh import get_hybrid_communicate_group, get_mesh
from .sharding import mark_sharding, shard_tensor


def _mp_size():
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size()
    mesh = get_mesh()
    return mesh.shape.get("mp", 1) if mesh is not None else 1


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mp_size()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        mark_sharding(self.weight, PartitionSpec(None, "mp"))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            mark_sharding(self.bias, PartitionSpec("mp"))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output and get_mesh() is not None and \
                "mp" in get_mesh().shape:
            nd = out.ndim
            out = shard_tensor(out, placements=[None] * (nd - 1) + ["mp"])
        return out


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mp_size()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        mark_sharding(self.weight, PartitionSpec("mp", None))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        # contraction dim sharded on mp → GSPMD inserts the all-reduce the
        # reference codes as c_allreduce_sum after the local matmul
        out = F.linear(x, self.weight, self.bias)
        return out


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.world_size = _mp_size()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        mark_sharding(self.weight, PartitionSpec("mp", None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax cross entropy (reference mp_layers.py:249 →
    c_softmax_with_cross_entropy op).  With logits sharded on the vocab axis,
    GSPMD partitions log_softmax's reduction into the same max/sum
    all-reduce pattern the hand-written kernel uses."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class RNGStatesTracker:
    """Per-region RNG isolation (reference: parallel_layers/random.py:32) —
    distinct named seeds for 'global' vs 'local' (per-mp-rank) dropout."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        import jax as _jax

        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        self.states_[name] = _jax.random.PRNGKey(seed)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        from ..ops import random as rnd

        @contextlib.contextmanager
        def ctx():
            if name not in self.states_:
                raise ValueError(f"unknown rng region {name}")
            gen = rnd.default_generator()
            saved = gen._key
            gen._key = self.states_[name]
            try:
                yield
            finally:
                self.states_[name] = gen._key
                gen._key = saved
        return ctx()


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    from ..ops import random as rnd

    seed = seed or (1024 + pyrandom.randint(0, 10000))
    global _RNG_TRACKER
    _RNG_TRACKER = RNGStatesTracker()
    rnd.seed(seed)
    _RNG_TRACKER.add("model_parallel_rng", seed + 1)
    _RNG_TRACKER.add("global_seed", seed + 2)
