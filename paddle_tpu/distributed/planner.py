"""Auto-parallel planner + cost model (reference:
python/paddle/distributed/auto_parallel/planner.py + cost_model.py —
search over per-tensor dims_mappings scored by a comm/memory cost model,
driven from Engine._plan).

TPU-native shape: candidates are GSPMD PartitionSpecs over the live mesh
axes instead of dims_mappings over process meshes, and the "reshard"
penalties of the reference become collective-bytes estimates (XLA inserts
the actual collectives).  The planner walks a Layer tree:

- per-parameter candidates: replicated, or split along any divisible dim
  over the model-parallel axis;
- alpha-beta cost: gradient-sync bytes (allreduce for replicated params,
  reduce-scatter fraction for sharded), activation collective bytes
  implied by the split (column-split -> allgather of the output,
  row-split -> allreduce of the output), and an HBM-pressure term that
  pushes large params to shard once the per-device budget is exceeded;
- Megatron pairing: consecutive Linear weights alternate column/row so
  the intermediate activation stays sharded and the pair needs ONE
  collective (the mp_layers pattern the manual API encodes by hand).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Candidate:
    spec: tuple                 # PartitionSpec entries (None | axis name)
    comm_bytes: float           # per-step collective traffic
    mem_bytes: float            # per-device parameter memory

    def cost(self, mem_pressure):
        # alpha-beta: latency folded into a constant per collective;
        # memory converts to cost only under pressure
        return self.comm_bytes + mem_pressure * self.mem_bytes


class CostModel:
    """Per-candidate cost estimates (reference cost_model.py estimates
    op runtime + transfer time on a cluster description; here bandwidth
    ratios are all that matter for ranking, so bytes ARE the units)."""

    LATENCY_BYTES = 128 * 1024  # alpha term per extra collective

    def __init__(self, mesh, batch_tokens=4096):
        self.mesh = mesh
        self.batch_tokens = batch_tokens

    def candidates(self, shape, dtype_size, axis="mp") -> List[Candidate]:
        deg = self.mesh.shape.get(axis, 1)
        n = int(np.prod(shape)) * dtype_size
        out_features = shape[-1] if shape else 1
        out: List[Candidate] = []
        # replicated: dp grad allreduce moves ~2x param bytes; full copy
        out.append(Candidate(spec=(None,) * len(shape),
                             comm_bytes=2.0 * n, mem_bytes=float(n)))
        if deg > 1:
            for dim, size in enumerate(shape):
                if size % deg:
                    continue
                spec = [None] * len(shape)
                spec[dim] = axis
                # sharded grads sync with a reduce-scatter (1/deg bytes);
                # the activation collective depends on which matmul side
                # the split cuts:
                #   column split (last dim)  -> allgather the sharded
                #       output: ~tokens * out/deg * (deg-1) bytes moved
                #   row split (other dims)   -> allreduce the FULL-width
                #       partial output: ~2 * tokens * out bytes
                if len(shape) >= 2 and dim == len(shape) - 1:
                    act = self.batch_tokens * (size // deg) * (deg - 1) \
                        * dtype_size
                elif len(shape) >= 2:
                    act = 2.0 * self.batch_tokens * out_features * dtype_size
                else:
                    act = 0.0  # 1-D params ride their layer's collective
                # alpha term: each extra collective costs fixed latency
                # (bytes-equivalent), so tiny params prefer replication
                out.append(Candidate(spec=tuple(spec),
                                     comm_bytes=2.0 * n / deg + act
                                     + self.LATENCY_BYTES,
                                     mem_bytes=float(n) / deg))
        return out


class Planner:
    """Pick a PartitionSpec per parameter (reference planner.py searches
    dims_mapping assignments; the search here is greedy per-tensor with
    the Megatron column/row pairing applied to Linear chains)."""

    def __init__(self, mesh, mp_axis="mp", hbm_budget_bytes=None,
                 batch_tokens=4096):
        self.mesh = mesh
        self.mp_axis = mp_axis
        self.cost_model = CostModel(mesh, batch_tokens)
        self.hbm_budget = hbm_budget_bytes

    def _mem_pressure(self, total_param_bytes):
        if not self.hbm_budget:
            return 0.0
        over = total_param_bytes / self.hbm_budget
        return 0.0 if over <= 1.0 else 10.0 * (over - 1.0)

    def plan(self, model) -> Dict[str, tuple]:
        """name -> PartitionSpec entries for every parameter."""
        from ..nn.layer.common import Embedding, Linear

        params = list(model.named_parameters())

        def itemsize(p):
            try:
                return int(np.dtype(str(p._value.dtype)).itemsize)
            except TypeError:
                return 2 if "bfloat16" in str(p._value.dtype) else 4

        total = sum(int(np.prod(p.shape)) * itemsize(p) for _, p in params)
        pressure = self._mem_pressure(total)
        deg = self.mesh.shape.get(self.mp_axis, 1)

        plan: Dict[str, tuple] = {}
        # walk layers so Linear chains can alternate column/row
        linear_parity = 0
        for lname, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, Linear) and deg > 1:
                w = layer.weight  # [in, out]
                prefix = f"{lname}." if lname else ""
                col = (None, self.mp_axis)
                row = (self.mp_axis, None)
                ok_col = w.shape[1] % deg == 0
                ok_row = w.shape[0] % deg == 0
                if ok_col and (linear_parity == 0 or not ok_row):
                    plan[f"{prefix}weight"] = col
                    if getattr(layer, "bias", None) is not None:
                        plan[f"{prefix}bias"] = (self.mp_axis,)
                    linear_parity = 1
                elif ok_row:
                    plan[f"{prefix}weight"] = row
                    if getattr(layer, "bias", None) is not None:
                        plan[f"{prefix}bias"] = (None,)
                    linear_parity = 0
            elif isinstance(layer, Embedding) and deg > 1:
                w = layer.weight  # [vocab, dim]
                prefix = f"{lname}." if lname else ""
                if w.shape[0] % deg == 0:
                    plan[f"{prefix}weight"] = (self.mp_axis, None)

        # everything else: cheapest candidate by the cost model
        for name, p in params:
            if name in plan:
                continue
            cands = self.cost_model.candidates(
                tuple(int(s) for s in p.shape), itemsize(p),
                axis=self.mp_axis)
            best = min(cands, key=lambda c: c.cost(pressure))
            plan[name] = best.spec
        return plan

    def apply(self, model, plan: Optional[Dict[str, tuple]] = None):
        """Annotate parameters with the planned shardings (GSPMD does the
        partitioning; reference partitioner.py rewrites the program)."""
        from jax.sharding import PartitionSpec

        from .sharding import mark_sharding

        plan = plan or self.plan(model)
        for name, p in model.named_parameters():
            spec = plan.get(name)
            if spec is None:
                continue
            mark_sharding(p, PartitionSpec(*spec))
        return plan
