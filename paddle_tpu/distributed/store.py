"""TCPStore python binding (reference: paddle/fluid/distributed/store/
tcp_store.h:91 bound via pybind; here the C++ core is loaded with ctypes).

The native library compiles on first use (g++ -O2 -shared); a pure-python
fallback keeps the API available without a toolchain.
"""
from __future__ import annotations

import ctypes
import threading

_lib = None
_lib_lock = threading.Lock()


def _load_native():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ..core.native.build import load_native

        lib = load_native("tcp_store")
        lib.tcp_store_server_start.restype = ctypes.c_void_p
        lib.tcp_store_server_start.argtypes = [ctypes.c_int]
        lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcp_store_client_connect.restype = ctypes.c_void_p
        lib.tcp_store_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                                 ctypes.c_int]
        lib.tcp_store_client_close.argtypes = [ctypes.c_void_p]
        lib.tcp_store_set.restype = ctypes.c_int
        lib.tcp_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_uint32]
        lib.tcp_store_get.restype = ctypes.c_int64
        lib.tcp_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_uint32]
        lib.tcp_store_add.restype = ctypes.c_int64
        lib.tcp_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int64]
        lib.tcp_store_wait.restype = ctypes.c_int
        lib.tcp_store_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint32]
        lib.tcp_store_delete.restype = ctypes.c_int
        lib.tcp_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib = lib
        return lib


class TCPStore:
    """paddle.distributed TCPStore analog.

    is_master=True starts the native server in-process; every rank (master
    included) connects a client to host:port.
    """

    def __init__(self, host="127.0.0.1", port=6170, is_master=False,
                 world_size=1, timeout=30.0):
        self._lib = _load_native()
        self._server = None
        self.host = host
        self.port = port
        self.world_size = world_size
        if is_master:
            self._server = self._lib.tcp_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
        self._client = self._lib.tcp_store_client_connect(
            host.encode(), port, int(timeout * 1000))
        if not self._client:
            raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")

    def set(self, key: str, value):
        data = value if isinstance(value, bytes) else str(value).encode()
        rc = self._lib.tcp_store_set(self._client, key.encode(), data,
                                     len(data))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str, wait: bool = True, timeout: float = 30.0) -> bytes:
        if wait:
            self.wait([key], timeout)
        buf = ctypes.create_string_buffer(1 << 20)
        n = self._lib.tcp_store_get(self._client, key.encode(), buf,
                                    len(buf))
        # value larger than the probe buffer (tcp_store_get reports the
        # full length and copies a prefix): refetch with the right size —
        # looping because the value can grow again between fetches
        refetches = 0
        while n > len(buf):
            if refetches >= 8:
                raise RuntimeError(
                    f"TCPStore.get: value for {key!r} kept growing across "
                    f"{refetches} refetches")
            refetches += 1
            buf = ctypes.create_string_buffer(int(n))
            n = self._lib.tcp_store_get(self._client, key.encode(), buf,
                                        len(buf))
        if n == -1:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    def add(self, key: str, amount: int = 1) -> int:
        out = self._lib.tcp_store_add(self._client, key.encode(), amount)
        if out == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return out

    def wait(self, keys, timeout: float = 30.0):
        if isinstance(keys, str):
            keys = [keys]
        for key in keys:
            rc = self._lib.tcp_store_wait(self._client, key.encode(),
                                          int(timeout * 1000))
            if rc != 1:
                raise TimeoutError(f"TCPStore.wait timeout on {key!r}")

    def delete_key(self, key: str):
        self._lib.tcp_store_delete(self._client, key.encode())

    def barrier(self, name: str = "barrier", timeout: float = 30.0):
        """All world_size participants arrive before anyone proceeds."""
        count = self.add(f"{name}/count", 1)
        if count == self.world_size:
            self.set(f"{name}/done", b"1")
        self.wait([f"{name}/done"], timeout)

    def __del__(self):
        try:
            if getattr(self, "_client", None):
                self._lib.tcp_store_client_close(self._client)
            if getattr(self, "_server", None):
                self._lib.tcp_store_server_stop(self._server)
        except Exception:
            pass
