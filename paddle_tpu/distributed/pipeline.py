"""Pipeline parallelism.

Reference: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(1F1B microbatch schedule over per-rank processes, P2P send_v2/recv_v2) and
pp_layers.py (PipelineLayer segmentation).

TPU-native design — no per-rank processes: the repeated-layer body is
*stacked* with a leading [pp] axis sharded over the mesh's pp axis, and the
schedule is ONE compiled program: lax.scan over (microbatches + stages - 1)
ticks, rotating activations one hop per tick with lax.ppermute over ICI
(GPipe skew).  Differentiating through the scan yields the reverse schedule
automatically, so forward+backward+update still compile into a single XLA
program — the bubble is the same as the reference's F-then-B schedule.

Heterogeneous head/tail (embedding, lm head) stay outside the pipelined body
(replicated or tensor-parallel), matching how the reference places shared
embeddings (SharedLayerDesc).
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from .mesh import get_mesh, shard_map_compat as _shard_map


def gpipe_spmd(stage_fn: Callable, stacked_params, x_microbatches,
               mesh: Optional[Mesh] = None, axis_name: str = "pp"):
    """Run a pipelined stack.

    stage_fn(local_params, x) -> y : applies ONE pipeline stage (its share of
        the repeated layers); local_params leaves have the leading [pp] axis
        already consumed (shape [layers_per_stage, ...]).
    stacked_params: pytree with leading axis pp_degree on every leaf.
    x_microbatches: [n_micro, micro_batch, ...] activations entering stage 0.

    Returns [n_micro, micro_batch, ...] outputs of the last stage.
    """
    mesh = mesh or get_mesh()
    n_stages = mesh.shape[axis_name]
    n_micro = x_microbatches.shape[0]
    ticks = n_micro + n_stages - 1

    def local(params_local, xs_local):
        # params_local: [1, layers_per_stage, ...] (pp axis consumed to 1)
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        zero = jnp.zeros_like(xs_local[0])
        outputs0 = jnp.zeros_like(xs_local)

        def tick(carry, t):
            prev_out, outputs = carry
            recv = jax.lax.ppermute(prev_out, axis_name, perm)
            mb = t - stage
            valid = (mb >= 0) & (mb < n_micro)
            x_in = jnp.where(stage == 0,
                             xs_local[jnp.clip(mb, 0, n_micro - 1)], recv)
            y = stage_fn(params_local, x_in)
            y = jnp.where(valid, y, zero)
            is_last = stage == n_stages - 1
            idx = jnp.clip(mb, 0, n_micro - 1)
            outputs = outputs.at[idx].set(
                jnp.where(is_last & valid, y, outputs[idx]))
            return (y, outputs), None

        (last, outputs), _ = jax.lax.scan(tick, (zero, outputs0),
                                          jnp.arange(ticks))
        # outputs are nonzero only on the last stage; psum broadcasts them
        return jax.lax.psum(outputs, axis_name)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    fn = _shard_map(local, mesh, (param_specs, P()), P())
    return fn(stacked_params, x_microbatches)


def pipeline_1f1b(stage_fn: Callable, stacked_params, shared_params,
                  inputs_mb, targets_mb, act_example,
                  mesh: Optional[Mesh] = None, axis_name: str = "pp",
                  data_axis: Optional[str] = None):
    """Synchronous 1F1B pipeline schedule, compiled into ONE XLA program.

    Reference semantics: fleet/meta_parallel/pipeline_parallel.py:81
    (forward_backward_pipeline warmup/steady/cooldown) with P2P via
    pp_utils/p2p_communication.py:217 _p2p_helper.  TPU-native design: the
    schedule is a lax.scan over ticks inside shard_map over the pp mesh
    axis; P2P hops are lax.ppermute over ICI (forward activations one hop
    down, backward grads one hop up, both per tick).  Unlike gpipe_spmd
    (autodiff through the scan → all microbatch activations live through
    the F phase), each stage here runs its OWN vjp per tick and stores only
    the stage *inputs* still in flight — at most min(M, 2*S-1) microbatches
    — recomputing the stage forward in the backward tick (activation
    recompute, reference fleet/utils/recompute.py).  Heterogeneous stages
    are first-class: stage_fn receives the stage index and applies
    embedding at stage 0 / head+loss at stage S-1 (reference
    SharedLayerDesc placement); shared-param grads (tied embeddings) are
    summed across stages by the closing psum — the reference's
    shared-embedding allreduce (pipeline_parallel.py _broadcast).

    Args:
      stage_fn(stage, shared, local, x, mb_inputs, mb_targets) -> (y, loss)
        stage: traced int32 stage id.  local: this stage's slice of
        stacked_params (leading S axis consumed).  x: activation with
        act_example's shape — ignored by stage 0, which embeds mb_inputs.
        y must have act_example's shape; loss must be this microbatch's
        scalar loss at stage S-1 and 0.0 elsewhere.
      stacked_params: pytree, every leaf with leading axis S.
      shared_params: pytree replicated to every stage (embedding, final
        norm, lm head, ...).
      inputs_mb / targets_mb: [M, micro, ...] microbatched tokens/labels.
      act_example: zeros with the canonical activation shape [micro, ...].
      data_axis: optional mesh axis the microbatch dim is sharded over
        (DP); grads/loss are psum-averaged over it.

    Returns (mean_loss, grads_stacked, grads_shared) — grads laid out like
    the corresponding params.
    """
    mesh = mesh or get_mesh()
    n_stages = mesh.shape[axis_name]
    M = inputs_mb.shape[0]
    S = n_stages
    ticks = M + 2 * (S - 1)
    depth = min(M, 2 * S - 1)
    dp_size = mesh.shape.get(data_axis, 1) if data_axis else 1

    def local_fn(stacked_local, shared, inputs, targets):
        stage = jax.lax.axis_index(axis_name)
        local = jax.tree_util.tree_map(lambda p: p[0], stacked_local)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [((i + 1) % S, i) for i in range(S)]
        zero_act = jnp.zeros_like(act_example)
        act_buf0 = jnp.zeros((depth,) + act_example.shape,
                             act_example.dtype)
        g_local0 = jax.tree_util.tree_map(jnp.zeros_like, local)
        g_shared0 = jax.tree_util.tree_map(jnp.zeros_like, shared)

        def tick(carry, t):
            fwd_msg, bwd_msg, act_buf, g_local, g_shared, loss_sum = carry
            x_recv = jax.lax.ppermute(fwd_msg, axis_name, fwd_perm)
            g_recv = jax.lax.ppermute(bwd_msg, axis_name, bwd_perm)

            f_mb = t - stage
            b_mb = t - (2 * (S - 1) - stage)
            f_valid = (f_mb >= 0) & (f_mb < M)
            b_valid = (b_mb >= 0) & (b_mb < M)
            f_idx = jnp.clip(f_mb, 0, M - 1)
            b_idx = jnp.clip(b_mb, 0, M - 1)

            # ---- forward: one microbatch down the pipe ----
            slot_f = f_idx % depth
            act_buf = act_buf.at[slot_f].set(
                jnp.where(f_valid, x_recv, act_buf[slot_f]))
            y, loss_f = stage_fn(stage, shared, local, x_recv,
                                 inputs[f_idx], targets[f_idx])
            fwd_next = jnp.where(f_valid, y, zero_act)
            loss_sum = loss_sum + jnp.where(
                f_valid, loss_f.astype(jnp.float32), 0.0)

            # ---- backward: vjp at the stored stage input ----
            # vjp is linear in the cotangent, so zero cotangents on
            # invalid/non-participating ticks yield zero grads; the
            # explicit masks below only guard against NaN from garbage
            # buffer slots.
            x_b = act_buf[b_idx % depth]
            last = stage == S - 1

            def fb(sh, lo, xx):
                return stage_fn(stage, sh, lo, xx, inputs[b_idx],
                                targets[b_idx])

            (y_b, loss_b), vjp_fn = jax.vjp(fb, shared, local, x_b)
            g_y = jnp.where(last, jnp.zeros_like(y_b),
                            g_recv.astype(y_b.dtype))
            g_loss = jnp.where(last & b_valid, 1.0 / M, 0.0).astype(
                loss_b.dtype)
            d_shared, d_local, d_x = vjp_fn((g_y, g_loss))
            mask = b_valid
            g_local = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(mask, g, jnp.zeros_like(g)),
                g_local, d_local)
            g_shared = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(mask, g, jnp.zeros_like(g)),
                g_shared, d_shared)
            bwd_next = jnp.where(mask, d_x, zero_act)

            return (fwd_next, bwd_next, act_buf, g_local, g_shared,
                    loss_sum), None

        carry0 = (zero_act, zero_act, act_buf0, g_local0, g_shared0,
                  jnp.float32(0.0))
        (fw, bw, buf, g_local, g_shared, loss_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(ticks))

        loss = jax.lax.psum(loss_sum, axis_name) / M
        g_shared = jax.lax.psum(g_shared, axis_name)
        if data_axis is not None and dp_size > 1:
            loss = jax.lax.psum(loss, data_axis) / dp_size
            g_shared = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, data_axis) / dp_size, g_shared)
            g_local = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, data_axis) / dp_size, g_local)
        g_stacked = jax.tree_util.tree_map(lambda g: g[None], g_local)
        return loss, g_stacked, g_shared

    pp_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    rep = jax.tree_util.tree_map(lambda _: P(), shared_params)
    mb_spec = (P(None, data_axis) if data_axis is not None else P())
    fn = _shard_map(local_fn, mesh,
                    (pp_specs, rep, mb_spec, mb_spec),
                    (P(), pp_specs, rep))
    return fn(stacked_params, shared_params, inputs_mb, targets_mb)


class LayerDesc:
    """Deferred layer construction (reference: pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr=
                 "weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Reference pp_layers.py:159 analog.

    Segments `layers` (Layers or LayerDescs) into pp stages.  In this
    single-controller build every stage's layers are materialized in the one
    process; when a pp mesh axis exists and the body is homogeneous, forward
    uses the compiled collective pipeline (gpipe_spmd) — otherwise it runs
    the stack sequentially (identical math, no pipelining), which is also
    the pp_degree=1 path.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        descs = list(layers)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d
                 for d in descs]
        self.run_function = built
        self._loss_fn = loss_fn
        mesh = get_mesh()
        self._num_stages = num_stages or (
            mesh.shape.get("pp", 1) if mesh is not None else 1)
        from .layers_helper import segment_uniform

        self._segments = segment_uniform(len(built), self._num_stages)
        for i, layer in enumerate(built):
            self.add_sublayer(str(i), layer)

    def get_stage_layers(self, stage_id):
        lo, hi = self._segments[stage_id]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


class PipelineParallel(nn.Layer):
    """Reference pipeline_parallel.py:31 wrapper: train_batch with the
    microbatch schedule.  Compiled-schedule path for homogeneous bodies via
    pipeline_stack()."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = (strategy.pipeline_configs.get(
            "accumulate_steps", 1) if strategy is not None else 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatch accumulation loop (F-then-B over microbatches)."""
        x, y = data
        n = self.accumulate_steps
        from ..ops.manipulation import split

        micro_x = split(x, n, axis=0) if n > 1 else [x]
        micro_y = split(y, n, axis=0) if n > 1 else [y]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._loss(out, my) / n
            loss.backward()
            total = loss if total is None else total + loss.detach()
        optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def _loss(self, out, label):
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if loss_fn is None:
            from ..nn import functional as F

            return F.cross_entropy(out, label)
        return loss_fn(out, label)
