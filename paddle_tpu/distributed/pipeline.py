"""Pipeline parallelism.

Reference: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(1F1B microbatch schedule over per-rank processes, P2P send_v2/recv_v2) and
pp_layers.py (PipelineLayer segmentation).

TPU-native design — no per-rank processes: the repeated-layer body is
*stacked* with a leading [pp] axis sharded over the mesh's pp axis, and the
schedule is ONE compiled program: lax.scan over (microbatches + stages - 1)
ticks, rotating activations one hop per tick with lax.ppermute over ICI
(GPipe skew).  Differentiating through the scan yields the reverse schedule
automatically, so forward+backward+update still compile into a single XLA
program — the bubble is the same as the reference's F-then-B schedule.

Heterogeneous head/tail (embedding, lm head) stay outside the pipelined body
(replicated or tensor-parallel), matching how the reference places shared
embeddings (SharedLayerDesc).
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from .mesh import get_mesh, shard_map_compat as _shard_map


def gpipe_spmd(stage_fn: Callable, stacked_params, x_microbatches,
               mesh: Optional[Mesh] = None, axis_name: str = "pp"):
    """Run a pipelined stack.

    stage_fn(local_params, x) -> y : applies ONE pipeline stage (its share of
        the repeated layers); local_params leaves have the leading [pp] axis
        already consumed (shape [layers_per_stage, ...]).
    stacked_params: pytree with leading axis pp_degree on every leaf.
    x_microbatches: [n_micro, micro_batch, ...] activations entering stage 0.

    Returns [n_micro, micro_batch, ...] outputs of the last stage.
    """
    mesh = mesh or get_mesh()
    n_stages = mesh.shape[axis_name]
    n_micro = x_microbatches.shape[0]
    ticks = n_micro + n_stages - 1

    def local(params_local, xs_local):
        # params_local: [1, layers_per_stage, ...] (pp axis consumed to 1)
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        zero = jnp.zeros_like(xs_local[0])
        outputs0 = jnp.zeros_like(xs_local)

        def tick(carry, t):
            prev_out, outputs = carry
            recv = jax.lax.ppermute(prev_out, axis_name, perm)
            mb = t - stage
            valid = (mb >= 0) & (mb < n_micro)
            x_in = jnp.where(stage == 0,
                             xs_local[jnp.clip(mb, 0, n_micro - 1)], recv)
            y = stage_fn(params_local, x_in)
            y = jnp.where(valid, y, zero)
            is_last = stage == n_stages - 1
            idx = jnp.clip(mb, 0, n_micro - 1)
            outputs = outputs.at[idx].set(
                jnp.where(is_last & valid, y, outputs[idx]))
            return (y, outputs), None

        (last, outputs), _ = jax.lax.scan(tick, (zero, outputs0),
                                          jnp.arange(ticks))
        # outputs are nonzero only on the last stage; psum broadcasts them
        return jax.lax.psum(outputs, axis_name)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    fn = _shard_map(local, mesh, (param_specs, P()), P())
    return fn(stacked_params, x_microbatches)


class LayerDesc:
    """Deferred layer construction (reference: pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr=
                 "weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Reference pp_layers.py:159 analog.

    Segments `layers` (Layers or LayerDescs) into pp stages.  In this
    single-controller build every stage's layers are materialized in the one
    process; when a pp mesh axis exists and the body is homogeneous, forward
    uses the compiled collective pipeline (gpipe_spmd) — otherwise it runs
    the stack sequentially (identical math, no pipelining), which is also
    the pp_degree=1 path.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        descs = list(layers)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d
                 for d in descs]
        self.run_function = built
        self._loss_fn = loss_fn
        mesh = get_mesh()
        self._num_stages = num_stages or (
            mesh.shape.get("pp", 1) if mesh is not None else 1)
        from .layers_helper import segment_uniform

        self._segments = segment_uniform(len(built), self._num_stages)
        for i, layer in enumerate(built):
            self.add_sublayer(str(i), layer)

    def get_stage_layers(self, stage_id):
        lo, hi = self._segments[stage_id]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


class PipelineParallel(nn.Layer):
    """Reference pipeline_parallel.py:31 wrapper: train_batch with the
    microbatch schedule.  Compiled-schedule path for homogeneous bodies via
    pipeline_stack()."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = (strategy.pipeline_configs.get(
            "accumulate_steps", 1) if strategy is not None else 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatch accumulation loop (F-then-B over microbatches)."""
        x, y = data
        n = self.accumulate_steps
        from ..ops.manipulation import split

        micro_x = split(x, n, axis=0) if n > 1 else [x]
        micro_y = split(y, n, axis=0) if n > 1 else [y]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._loss(out, my) / n
            loss.backward()
            total = loss if total is None else total + loss.detach()
        optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def _loss(self, out, label):
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if loss_fn is None:
            from ..nn import functional as F

            return F.cross_entropy(out, label)
        return loss_fn(out, label)
