"""Pipeline parallelism.

Reference: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(1F1B microbatch schedule over per-rank processes, P2P send_v2/recv_v2) and
pp_layers.py (PipelineLayer segmentation).

TPU-native design — no per-rank processes: the repeated-layer body is
*stacked* with a leading [pp] axis sharded over the mesh's pp axis, and the
schedule is ONE compiled program: lax.scan over (microbatches + stages - 1)
ticks, rotating activations one hop per tick with lax.ppermute over ICI
(GPipe skew).  Differentiating through the scan yields the reverse schedule
automatically, so forward+backward+update still compile into a single XLA
program — the bubble is the same as the reference's F-then-B schedule.

Heterogeneous head/tail (embedding, lm head) stay outside the pipelined body
(replicated or tensor-parallel), matching how the reference places shared
embeddings (SharedLayerDesc).
"""
from __future__ import annotations

import dataclasses
import functools
import hashlib
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..core.dispatch import apply
from ..core.tensor import Tensor
from .mesh import get_mesh, shard_map_compat as _shard_map


def gpipe_spmd(stage_fn: Callable, stacked_params, x_microbatches,
               mesh: Optional[Mesh] = None, axis_name: str = "pp"):
    """Run a pipelined stack.

    stage_fn(local_params, x) -> y : applies ONE pipeline stage (its share of
        the repeated layers); local_params leaves have the leading [pp] axis
        already consumed (shape [layers_per_stage, ...]).
    stacked_params: pytree with leading axis pp_degree on every leaf.
    x_microbatches: [n_micro, micro_batch, ...] activations entering stage 0.

    Returns [n_micro, micro_batch, ...] outputs of the last stage.
    """
    mesh = mesh or get_mesh()
    n_stages = mesh.shape[axis_name]
    n_micro = x_microbatches.shape[0]
    ticks = n_micro + n_stages - 1

    def local(params_local, xs_local):
        # params_local: [1, layers_per_stage, ...] (pp axis consumed to 1)
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        zero = jnp.zeros_like(xs_local[0])
        outputs0 = jnp.zeros_like(xs_local)

        def tick(carry, t):
            prev_out, outputs = carry
            recv = jax.lax.ppermute(prev_out, axis_name, perm)
            mb = t - stage
            valid = (mb >= 0) & (mb < n_micro)
            x_in = jnp.where(stage == 0,
                             xs_local[jnp.clip(mb, 0, n_micro - 1)], recv)
            y = stage_fn(params_local, x_in)
            y = jnp.where(valid, y, zero)
            is_last = stage == n_stages - 1
            idx = jnp.clip(mb, 0, n_micro - 1)
            outputs = outputs.at[idx].set(
                jnp.where(is_last & valid, y, outputs[idx]))
            return (y, outputs), None

        (last, outputs), _ = jax.lax.scan(tick, (zero, outputs0),
                                          jnp.arange(ticks))
        # outputs are nonzero only on the last stage; psum broadcasts them
        return jax.lax.psum(outputs, axis_name)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    fn = _shard_map(local, mesh, (param_specs, P()), P())
    return fn(stacked_params, x_microbatches)


def pipeline_1f1b(stage_fn: Callable, stacked_params, shared_params,
                  inputs_mb, targets_mb, act_example,
                  mesh: Optional[Mesh] = None, axis_name: str = "pp",
                  data_axis: Optional[str] = None,
                  stacked_specs=None, shared_specs=None,
                  manual_axes: Optional[dict] = None):
    """Synchronous 1F1B pipeline schedule, compiled into ONE XLA program.

    Reference semantics: fleet/meta_parallel/pipeline_parallel.py:81
    (forward_backward_pipeline warmup/steady/cooldown) with P2P via
    pp_utils/p2p_communication.py:217 _p2p_helper.  TPU-native design: the
    schedule is a lax.scan over ticks inside shard_map over the pp mesh
    axis; P2P hops are lax.ppermute over ICI (forward activations one hop
    down, backward grads one hop up, both per tick).  Unlike gpipe_spmd
    (autodiff through the scan → all microbatch activations live through
    the F phase), each stage here runs its OWN vjp per tick and stores only
    the stage *inputs* still in flight — at most min(M, 2*S-1) microbatches
    — recomputing the stage forward in the backward tick (activation
    recompute, reference fleet/utils/recompute.py).  Heterogeneous stages
    are first-class: stage_fn receives the stage index and applies
    embedding at stage 0 / head+loss at stage S-1 (reference
    SharedLayerDesc placement); shared-param grads (tied embeddings) are
    summed across stages by the closing psum — the reference's
    shared-embedding allreduce (pipeline_parallel.py _broadcast).

    Args:
      stage_fn(stage, shared, local, x, mb_inputs, mb_targets) -> (y, loss)
        stage: traced int32 stage id.  local: this stage's slice of
        stacked_params (leading S axis consumed).  x: activation with
        act_example's shape — ignored by stage 0, which embeds mb_inputs.
        y must have act_example's shape; loss must be this microbatch's
        scalar loss at stage S-1 and 0.0 elsewhere.
      stacked_params: pytree, every leaf with leading axis S.
      shared_params: pytree replicated to every stage (embedding, final
        norm, lm head, ...).
      inputs_mb / targets_mb: [M, micro, ...] microbatched tokens/labels.
      act_example: zeros with the canonical activation shape [micro, ...].
      data_axis: optional mesh axis the microbatch dim is sharded over
        (DP); grads/loss are psum-averaged over it.
      stacked_specs / shared_specs: optional per-leaf PartitionSpecs for
        TP×PP composition — stacked leaves default to P(axis_name) and
        shared to replicated; pass specs carrying 'mp' entries to hand
        each pp stage mp-LOCAL weight shards (reference: topology.py:133
        composes all four axes in one HybridCommunicateGroup).
      manual_axes: {axis: size} activated via manual_collective_axes
        around stage tracing so TP layers emit explicit collectives.

    Returns (mean_loss, grads_stacked, grads_shared) — grads laid out like
    the corresponding params.
    """
    mesh = mesh or get_mesh()
    n_stages = mesh.shape[axis_name]
    M = inputs_mb.shape[0]
    S = n_stages
    ticks = M + 2 * (S - 1)
    depth = min(M, 2 * S - 1)
    dp_size = mesh.shape.get(data_axis, 1) if data_axis else 1

    def local_fn(stacked_local, shared, inputs, targets):
        from .parallel_layers import manual_collective_axes

        with manual_collective_axes(manual_axes or {}):
            return _local_fn_body(stacked_local, shared, inputs, targets)

    def _local_fn_body(stacked_local, shared, inputs, targets):
        stage = jax.lax.axis_index(axis_name)
        local = jax.tree_util.tree_map(lambda p: p[0], stacked_local)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [((i + 1) % S, i) for i in range(S)]
        zero_act = jnp.zeros_like(act_example)
        act_buf0 = jnp.zeros((depth,) + act_example.shape,
                             act_example.dtype)
        g_local0 = jax.tree_util.tree_map(jnp.zeros_like, local)
        g_shared0 = jax.tree_util.tree_map(jnp.zeros_like, shared)

        def tick(carry, t):
            fwd_msg, bwd_msg, act_buf, g_local, g_shared, loss_sum = carry
            x_recv = jax.lax.ppermute(fwd_msg, axis_name, fwd_perm)
            g_recv = jax.lax.ppermute(bwd_msg, axis_name, bwd_perm)

            f_mb = t - stage
            b_mb = t - (2 * (S - 1) - stage)
            f_valid = (f_mb >= 0) & (f_mb < M)
            b_valid = (b_mb >= 0) & (b_mb < M)
            f_idx = jnp.clip(f_mb, 0, M - 1)
            b_idx = jnp.clip(b_mb, 0, M - 1)

            # ---- forward: one microbatch down the pipe ----
            slot_f = f_idx % depth
            act_buf = act_buf.at[slot_f].set(
                jnp.where(f_valid, x_recv, act_buf[slot_f]))
            y, loss_f = stage_fn(stage, shared, local, x_recv,
                                 inputs[f_idx], targets[f_idx])
            fwd_next = jnp.where(f_valid, y, zero_act)
            loss_sum = loss_sum + jnp.where(
                f_valid, loss_f.astype(jnp.float32), 0.0)

            # ---- backward: vjp at the stored stage input ----
            # vjp is linear in the cotangent, so zero cotangents on
            # invalid/non-participating ticks yield zero grads; the
            # explicit masks below only guard against NaN from garbage
            # buffer slots.
            x_b = act_buf[b_idx % depth]
            last = stage == S - 1

            def fb(sh, lo, xx):
                return stage_fn(stage, sh, lo, xx, inputs[b_idx],
                                targets[b_idx])

            (y_b, loss_b), vjp_fn = jax.vjp(fb, shared, local, x_b)
            g_y = jnp.where(last, jnp.zeros_like(y_b),
                            g_recv.astype(y_b.dtype))
            g_loss = jnp.where(last & b_valid, 1.0 / M, 0.0).astype(
                loss_b.dtype)
            d_shared, d_local, d_x = vjp_fn((g_y, g_loss))
            mask = b_valid
            g_local = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(mask, g, jnp.zeros_like(g)),
                g_local, d_local)
            g_shared = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(mask, g, jnp.zeros_like(g)),
                g_shared, d_shared)
            bwd_next = jnp.where(mask, d_x, zero_act)

            return (fwd_next, bwd_next, act_buf, g_local, g_shared,
                    loss_sum), None

        carry0 = (zero_act, zero_act, act_buf0, g_local0, g_shared0,
                  jnp.float32(0.0))
        (fw, bw, buf, g_local, g_shared, loss_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(ticks))

        loss = jax.lax.psum(loss_sum, axis_name) / M
        g_shared = jax.lax.psum(g_shared, axis_name)
        if data_axis is not None and dp_size > 1:
            loss = jax.lax.psum(loss, data_axis) / dp_size
            g_shared = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, data_axis) / dp_size, g_shared)
            g_local = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, data_axis) / dp_size, g_local)
        g_stacked = jax.tree_util.tree_map(lambda g: g[None], g_local)
        return loss, g_stacked, g_shared

    pp_specs = (stacked_specs if stacked_specs is not None else
                jax.tree_util.tree_map(lambda _: P(axis_name),
                                       stacked_params))
    rep = (shared_specs if shared_specs is not None else
           jax.tree_util.tree_map(lambda _: P(), shared_params))
    mb_spec = (P(None, data_axis) if data_axis is not None else P())
    fn = _shard_map(local_fn, mesh,
                    (pp_specs, rep, mb_spec, mb_spec),
                    (P(), pp_specs, rep))
    return fn(stacked_params, shared_params, inputs_mb, targets_mb)


class LayerDesc:
    """Deferred layer construction (reference: pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr=
                 "weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Reference pp_layers.py:159 analog.

    Segments `layers` (Layers or LayerDescs) into pp stages.  In this
    single-controller build every stage's layers are materialized in the one
    process; when a pp mesh axis exists and the body is homogeneous, forward
    uses the compiled collective pipeline (gpipe_spmd) — otherwise it runs
    the stack sequentially (identical math, no pipelining), which is also
    the pp_degree=1 path.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        descs = list(layers)
        built = [d.build_layer() if isinstance(d, LayerDesc) else d
                 for d in descs]
        self.run_function = built
        self._loss_fn = loss_fn
        mesh = get_mesh()
        self._num_stages = num_stages or (
            mesh.shape.get("pp", 1) if mesh is not None else 1)
        from .layers_helper import segment_uniform

        self._segments = segment_uniform(len(built), self._num_stages)
        for i, layer in enumerate(built):
            self.add_sublayer(str(i), layer)

    def get_stage_layers(self, stage_id):
        lo, hi = self._segments[stage_id]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


def functional_call(layer, values, *inputs):
    """Call an eager Layer as a PURE function of `values`.

    `values` is a list of raw jnp arrays in `layer.named_parameters()`
    order; `inputs` are raw arrays.  The layer's parameters are rebound to
    `values` for the duration of the call (and restored after), so tracing
    this under jax.vjp/jit differentiates with respect to `values` — the
    TPU-native analog of running a reference pipeline stage's sublayers
    under its rank-local autograd engine (pipeline_parallel.py
    _forward_step).  The call runs under no_grad + static-trace guards:
    the eager tape must not record tracer-valued ops.
    """
    from ..core import dispatch

    params = [p for _, p in layer.named_parameters()]
    if len(params) != len(values):
        raise ValueError(
            f"functional_call: layer has {len(params)} params, got "
            f"{len(values)} values")
    saved = [p._value for p in params]
    try:
        for p, v in zip(params, values):
            p._value = v
        with dispatch.no_grad_ctx(), dispatch.static_trace_guard():
            out = layer(*[x if isinstance(x, Tensor) else Tensor(x)
                          for x in inputs])
        if isinstance(out, Tensor):
            return out._value
        if hasattr(out, "dtype") and hasattr(out, "shape"):
            return out
        raise TypeError(
            f"functional_call: {type(layer).__name__} returned "
            f"{type(out).__name__}; compiled pipeline stages must return a "
            "single tensor")
    finally:
        for p, s in zip(params, saved):
            p._value = s


def _param_values(layer):
    return [p._value for _, p in layer.named_parameters()]


# Layer-machinery attrs excluded from the config signature: parameters are
# covered by the (shape, dtype) entries, buffers are frozen separately with
# their contents, and _hook_id is a registration counter with no behavior.
_SIG_SKIP = {"_parameters", "_sub_layers", "_buffers", "_hook_id"}


def _freeze_cfg(v):
    """Hashable, comparable-by-value digest of a config attribute.

    Scalars and (nested) containers compare by value; dataclasses by
    field values; concrete arrays by shape/dtype/content hash.  Anything
    else freezes to its object id — distinct instances then never compare
    equal, so layers carrying unrecognized state are conservatively
    treated as non-homogeneous and the pipeline falls back to the eager
    per-layer loop instead of silently running body[0]'s forward
    (ADVICE r3: tuple-valued knobs like kernel_size=(2,2) vs (3,3) were
    invisible to the old scalar-only signature)."""
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return v
    if isinstance(v, (tuple, list)):
        return ("seq", tuple(_freeze_cfg(e) for e in v))
    if isinstance(v, (set, frozenset)):
        return ("set", tuple(sorted(repr(e) for e in v)))
    if isinstance(v, dict):
        return ("dict", tuple(sorted(
            ((repr(k), _freeze_cfg(x)) for k, x in v.items()))))
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return ("dc", type(v).__name__, tuple(
            (f.name, _freeze_cfg(getattr(v, f.name)))
            for f in dataclasses.fields(v)))
    arr = getattr(v, "_value", v)
    if hasattr(arr, "shape") and hasattr(arr, "dtype"):
        try:  # concrete array: compare by content (tracers fall through)
            buf = np.asarray(arr)
            # full-content hash: np.asarray already pulled the buffer to
            # host and sha1 is ~1 ms / 4 MB at one-time program build; a
            # sampled or id() digest would either miss differing entries
            # (silently folding distinct layers into one homogeneous
            # body) or split byte-identical per-layer tables
            digest = hashlib.sha1(buf.tobytes()).hexdigest()
            return ("arr", buf.shape, str(buf.dtype), digest)
        except Exception:  # noqa: BLE001
            pass
    return ("opaque", id(v))


def _layer_sig(layer):
    """Structural signature used to find the homogeneous pipeline body.

    Includes the concrete class identity, every config attribute (public
    AND private — Conv-style layers keep stride/kernel_size in private
    attrs), forward hooks, and buffer contents, so two same-shaped layers
    with different behavior knobs (Block(act='relu') vs Block(act='gelu'),
    Conv2D(stride=1) vs Conv2D(stride=2), different rotary tables) do NOT
    count as homogeneous — they would silently run through stage 0's
    forward."""
    entries = tuple((n, tuple(p.shape), str(p._value.dtype))
                    for n, p in layer.named_parameters())

    def cfg_of(l):
        out = []
        for k in sorted(vars(l)):
            if k in _SIG_SKIP:
                continue
            out.append((k, _freeze_cfg(vars(l)[k])))
        out.append(("<buffers>", tuple(
            (bn, _freeze_cfg(b)) for bn, b in sorted(l._buffers.items())
            if b is not None)))
        return tuple(out)

    cfgs = tuple((id(type(sub)), cfg_of(sub))
                 for _, _, sub in layer._walk("", True))
    return (id(type(layer)), entries, cfgs)


def _split_stages(built, n_stages):
    """Partition a PipelineLayer's flat layer list into
    (prologue, body, epilogue): the body is the longest contiguous run of
    structurally identical layers, truncated to a multiple of n_stages
    (spare tail layers join the epilogue).  Mirrors how reference models
    are laid out for pp (pp_layers.py): embedding first, N identical
    blocks, norm + head last."""
    if not built:
        raise ValueError("PipelineLayer has no layers")
    sigs = [_layer_sig(l) for l in built]
    best_start, best_len = 0, 1
    start = 0
    for i in range(1, len(sigs) + 1):
        if i == len(sigs) or sigs[i] != sigs[start]:
            if i - start > best_len:
                best_start, best_len = start, i - start
            start = i
    body_len = (best_len // n_stages) * n_stages
    if body_len == 0:
        raise ValueError(
            f"no homogeneous body of >= {n_stages} layers found for "
            f"{n_stages} pipeline stages (longest run: {best_len})")
    prologue = built[:best_start]
    body = built[best_start:best_start + body_len]
    epilogue = built[best_start + body_len:]
    return prologue, body, epilogue


def _has_persistable_buffers(layers):
    for l in layers:
        for _, lp, sub in l._walk("", True):
            for bname, b in sub._buffers.items():
                if b is not None and \
                        bname not in sub._non_persistable_buffer_names:
                    return True
    return False


class Compiled1F1BProgram:
    """Generic PipelineLayer -> compiled 1F1B schedule (pipeline_1f1b).

    Reference semantics: pipeline_parallel.py:153 train_batch runs the
    1F1B schedule for ANY PipelineLayer's rank-local segment.  TPU-native:
    the homogeneous body is stacked over the pp mesh axis ([S, L/S, ...]
    leaves) and scanned per stage; prologue layers (e.g. embedding) run in
    stage 0's branch, epilogue layers (final norm, head) + loss in stage
    S-1's, matching SharedLayerDesc placement.  Parameters are read from
    the eager layers at each step and gradients written back to
    `param.grad`, so any eager Optimizer drives the update.

    Restrictions (fall back to the eager microbatch loop otherwise): the
    layer list must contain a homogeneous run of >= S layers, layers must
    be buffer-free (no BN running stats), and activations must be a single
    tensor between stages.
    """

    def __init__(self, pipeline_layer, mesh, axis_name="pp",
                 data_axis=None, loss_fn=None):
        self.mesh = mesh
        self.axis_name = axis_name
        self.data_axis = data_axis
        self.S = mesh.shape[axis_name]
        built = list(pipeline_layer.run_function)
        self.prologue, self.body, self.epilogue = _split_stages(built, self.S)
        if _has_persistable_buffers(built):
            raise ValueError("compiled 1F1B requires buffer-free layers")
        self.L = len(self.body)
        self._loss_fn = loss_fn
        self._jit_cache = {}
        # TP×PP composition: mesh axes (beyond pp/dp) that stage params
        # are sharded over; TP layers emit explicit collectives for these
        # under manual_collective_axes (reference: topology.py:133 4-axis
        # HybridCommunicateGroup — mp composes with pp in one program)
        self.manual_axes = {
            ax: mesh.shape[ax] for ax in ("mp",)
            if mesh.shape.get(ax, 1) > 1}

    def _leaf_entries(self, p):
        """Param sharding entries restricted to the manual (TP) axes."""
        from .sharding import get_sharding_spec

        spec = get_sharding_spec(p)
        if not spec:
            return ()
        return tuple(e if (isinstance(e, str) and e in self.manual_axes)
                     else None for e in spec)

    def read_specs(self):
        """Per-leaf PartitionSpecs mirroring read_params()'s structure."""
        from jax.sharding import PartitionSpec as P

        shared_specs = {
            key: [[P(*self._leaf_entries(p))
                   for _, p in l.named_parameters()] for l in layers]
            for key, layers in (("pro", self.prologue),
                                ("epi", self.epilogue))}
        body_params = [[p for _, p in l.named_parameters()]
                       for l in self.body]
        stacked_specs = []
        for j in range(len(body_params[0])):
            entries = self._leaf_entries(body_params[0][j])
            for other in body_params[1:]:
                if self._leaf_entries(other[j]) != entries:
                    raise ValueError(
                        "body layers disagree on TP sharding for leaf "
                        f"{j}; cannot stack over the pp axis")
            stacked_specs.append(P(self.axis_name, None, *entries))
        return shared_specs, tuple(stacked_specs)

    # ---- parameter packing -------------------------------------------
    def read_params(self):
        shared = {"pro": [_param_values(l) for l in self.prologue],
                  "epi": [_param_values(l) for l in self.epilogue]}
        n_leaves = len(_param_values(self.body[0]))
        stacked = []
        for j in range(n_leaves):
            leaf = jnp.stack([_param_values(l)[j] for l in self.body])
            stacked.append(leaf.reshape(
                (self.S, self.L // self.S) + leaf.shape[1:]))
        return shared, tuple(stacked)

    def write_grads(self, g_shared, g_stacked):
        def acc(p, g):
            g = g.astype(p._value.dtype)
            if p.grad is None:
                p.grad = Tensor(g, stop_gradient=True)
            else:
                p.grad = Tensor(p.grad._value + g, stop_gradient=True)

        for layers, grads in ((self.prologue, g_shared["pro"]),
                              (self.epilogue, g_shared["epi"])):
            for l, gvals in zip(layers, grads):
                for (_, p), g in zip(l.named_parameters(), gvals):
                    acc(p, g)
        for j, g in enumerate(g_stacked):
            flat = g.reshape((self.L,) + g.shape[2:])
            for i, l in enumerate(self.body):
                params = [p for _, p in l.named_parameters()]
                acc(params[j], flat[i])

    # ---- stage function ----------------------------------------------
    def _loss_value(self, out, target):
        from ..core import dispatch

        with dispatch.no_grad_ctx(), dispatch.static_trace_guard():
            if self._loss_fn is None:
                from ..nn import functional as F

                loss = F.cross_entropy(Tensor(out), Tensor(target))
            else:
                loss = self._loss_fn(Tensor(out), Tensor(target))
        raw = loss._value if isinstance(loss, Tensor) else loss
        return raw.astype(jnp.float32).reshape(())

    def make_stage_fn(self):
        prologue, body, epilogue = self.prologue, self.body, self.epilogue
        S = self.S
        proto = body[0]

        def stage_fn(stage, shared, local, x, mb_in, mb_tgt):
            def pro_branch():
                h = mb_in
                for l, vals in zip(prologue, shared["pro"]):
                    h = functional_call(l, vals, h)
                return h.astype(x.dtype)

            h = jax.lax.cond(stage == 0, pro_branch, lambda: x)

            def body_fn(hh, lp):
                return functional_call(proto, list(lp), hh), None

            h, _ = jax.lax.scan(body_fn, h, local)

            def loss_branch():
                out = h
                for l, vals in zip(epilogue, shared["epi"]):
                    out = functional_call(l, vals, out)
                return self._loss_value(out, mb_tgt)

            loss = jax.lax.cond(stage == S - 1, loss_branch,
                                lambda: jnp.float32(0.0))
            return h, loss

        return stage_fn

    def _act_example(self, shared, mb_in_example):
        """Shape/dtype of the inter-stage activation (prologue output)."""
        if not self.prologue:
            return jnp.zeros(mb_in_example.shape, mb_in_example.dtype)

        def f(vals, mb):
            h = mb
            for l, v in zip(self.prologue, vals):
                h = functional_call(l, v, h)
            return h

        out = jax.eval_shape(f, shared["pro"], mb_in_example)
        return jnp.zeros(out.shape, out.dtype)

    # ---- compiled step -----------------------------------------------
    def step(self, x_mb, y_mb):
        """Run one 1F1B step on microbatched arrays [M, micro, ...];
        returns (loss, g_stacked, g_shared) as raw arrays."""
        shared, stacked = self.read_params()
        key = (x_mb.shape, str(x_mb.dtype), y_mb.shape, str(y_mb.dtype))
        if key not in self._jit_cache:
            stage_fn = self.make_stage_fn()
            # activations inside shard_map are LOCAL shards: divide the
            # microbatch dim by the dp degree when it is mesh-sharded
            dp = (self.mesh.shape.get(self.data_axis, 1)
                  if self.data_axis else 1)
            if x_mb.shape[1] % dp:
                raise ValueError(
                    f"microbatch {x_mb.shape[1]} not divisible by dp={dp}")
            mb_local = jnp.zeros((x_mb.shape[1] // dp,) + x_mb.shape[2:],
                                 x_mb.dtype)
            act = self._act_example(shared, mb_local)
            shared_specs, stacked_specs = self.read_specs()

            def run(sh, st, xs, ys):
                return pipeline_1f1b(
                    stage_fn, st, sh, xs, ys, act, mesh=self.mesh,
                    axis_name=self.axis_name, data_axis=self.data_axis,
                    stacked_specs=stacked_specs, shared_specs=shared_specs,
                    manual_axes=self.manual_axes)

            self._jit_cache[key] = jax.jit(run)
        return self._jit_cache[key](shared, stacked, x_mb, y_mb)


class PipelineParallel(nn.Layer):
    """Reference pipeline_parallel.py:31 wrapper: train_batch with the
    microbatch schedule.  With a pp mesh axis of degree >= 2 and a
    compilable PipelineLayer, train_batch runs the compiled 1F1B schedule
    (reference forward_backward_pipeline, pipeline_parallel.py:81);
    otherwise it falls back to an eager F-then-B accumulation loop with
    identical math."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = (strategy.pipeline_configs.get(
            "accumulate_steps", 1) if strategy is not None else 1)
        self._1f1b = None
        self._1f1b_failed = False

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _get_1f1b(self):
        if self._1f1b is not None or self._1f1b_failed:
            return self._1f1b
        mesh = get_mesh()
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if pp <= 1:
            return None  # not latched: the mesh may be initialized later
        if not isinstance(self._layers, PipelineLayer):
            self._1f1b_failed = True
            return None
        data_axis = "dp" if mesh.shape.get("dp", 1) > 1 else None
        try:
            self._1f1b = Compiled1F1BProgram(
                self._layers, mesh, axis_name="pp", data_axis=data_axis,
                loss_fn=getattr(self._layers, "_loss_fn", None))
        except ValueError as e:
            import warnings

            warnings.warn(f"compiled 1F1B unavailable ({e}); "
                          "falling back to eager microbatch loop")
            self._1f1b_failed = True
        return self._1f1b

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        M = self.accumulate_steps
        B = x._value.shape[0]
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by accumulate_steps {M}")
        prog = self._get_1f1b() if scaler is None else None
        if prog is not None:
            micro = B // M
            dp = (prog.mesh.shape.get(prog.data_axis, 1)
                  if prog.data_axis else 1)
            if micro % dp:
                # this batch can't shard over dp; the eager loop can still
                # run it — a per-call fallback, not a latched failure
                prog = None
        if prog is not None:
            # only the compiled schedule itself is allowed to fall back;
            # grads/optimizer run outside the guard so a failing optimizer
            # can never cause a double-applied eager re-run
            # Until the program has stepped once, any failure (including
            # XlaRuntimeError from backend compilation — e.g. a Mosaic
            # tiling error that only surfaces on the real chip) is
            # deterministic "this model can't compile": latch + eager
            # fallback.  After a successful step, only trace-shaped error
            # types latch; a runtime fault (transient OOM while another
            # process holds the chip) propagates instead of silently
            # downgrading every later step (ADVICE r3).
            first_run = not getattr(prog, "_stepped_ok", False)
            latchable = ((Exception,) if first_run else
                         (TypeError, ValueError, IndexError,
                          NotImplementedError))
            try:
                loss, g_stacked, g_shared = self._run_1f1b(prog, x, y)
                prog._stepped_ok = True
            except latchable as e:  # noqa: BLE001 — see above
                import warnings

                warnings.warn(
                    f"compiled 1F1B step failed ({type(e).__name__}: "
                    f"{e}); falling back to the eager microbatch loop")
                self._1f1b = None
                self._1f1b_failed = True
            else:
                prog.write_grads(g_shared, g_stacked)
                optimizer.step()
                optimizer.clear_grad()
                if lr_scheduler is not None:
                    lr_scheduler.step()
                return Tensor(loss, stop_gradient=True)
        return self._train_batch_eager(x, y, optimizer, lr_scheduler,
                                       scaler)

    def _run_1f1b(self, prog, x, y):
        M = self.accumulate_steps
        xv, yv = x._value, y._value
        x_mb = xv.reshape((M, xv.shape[0] // M) + xv.shape[1:])
        y_mb = yv.reshape((M, yv.shape[0] // M) + yv.shape[1:])
        return prog.step(x_mb, y_mb)

    def _train_batch_eager(self, x, y, optimizer, lr_scheduler,
                           scaler=None):
        """Microbatch accumulation loop (F-then-B over microbatches);
        with a GradScaler, losses are scaled and the step goes through
        scaler.step/update (reference pipeline_parallel.py amp path)."""
        n = self.accumulate_steps
        from ..ops.manipulation import split

        micro_x = split(x, n, axis=0) if n > 1 else [x]
        micro_y = split(y, n, axis=0) if n > 1 else [y]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._loss(out, my) / n
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss if total is None else total + loss.detach()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total

    def _loss(self, out, label):
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if loss_fn is None:
            from ..nn import functional as F

            return F.cross_entropy(out, label)
        return loss_fn(out, label)
