"""paddle.distributed.utils (reference:
python/paddle/distributed/utils.py — global_scatter:57 / global_gather:179
over the global_scatter/global_gather collective ops used by MoE token
routing).

TPU-native shape: the reference ops move ragged per-expert token counts
with an MPI-style alltoallv.  XLA wants static shapes, so the routing
contract here is capacity-padded (the GShard formulation the MoE layer
uses — distributed/moe.py): tokens are laid out [world * n_local_expert,
capacity, d] and a single all_to_all over the expert-parallel axis swaps
the expert dim across ranks.  local_count/global_count are accepted for
API parity; when they are concrete they are sanity-checked against the
row count (the padded layout itself carries the routing, so ragged
counts have no effect beyond that check).
"""
from __future__ import annotations

import jax

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor
from .collective import _axis_in_scope, _group_axis


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _resolve_axis(group):
    """The mesh axis to route over: the group's axis if given, else the
    first in-scope candidate — conventionally "ep" (expert parallel),
    falling back to the global mesh's single axis or "world"."""
    candidates = []
    if group is not None:
        candidates.append(group.axis_name)
    else:
        candidates.extend(["ep", "expert"])
        candidates.append(_group_axis(None))
        candidates.append("world")
    for ax in candidates:
        if ax is not None and _axis_in_scope(ax):
            return ax
    return None


def _check_counts(x, counts, name):
    if counts is None:
        return
    import numpy as np

    try:
        vals = counts.numpy() if hasattr(counts, "numpy") else counts
        total = int(np.sum(np.asarray(vals)))
    except Exception:  # traced counts: nothing to check statically
        return
    rows = int(x.shape[0])
    if total != rows:
        raise ValueError(
            f"{name}: counts sum to {total} but x has {rows} rows — "
            f"this API routes by the capacity-padded layout; pad each "
            f"expert chunk to capacity")


def _routed_all_to_all(op_name, xt, group):
    """Shared scatter/gather body: they are the same involution over the
    expert-parallel axis, differing only in direction-of-meaning.
    Callers pass an already-converted Tensor."""
    ax = _resolve_axis(group)
    if ax is None:
        # single-rank world: routing is the identity (all experts local)
        return xt

    def _fn(v):
        return jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0,
                                  tiled=True)

    return apply(op_name, _fn, xt)


def global_scatter(x, local_count=None, global_count=None, group=None,
                   use_calc_stream=True):
    """Distribute capacity-padded expert batches to their owning ranks.

    x: [n_expert_global * capacity, d] (rank-local tokens grouped by
    destination expert, capacity-padded).  Returns the tokens this rank's
    experts receive from every rank: same shape, expert-major."""
    xt = _t(x)
    _check_counts(xt, local_count, "global_scatter")
    return _routed_all_to_all("global_scatter", xt, group)


def global_gather(x, local_count=None, global_count=None, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter: return expert outputs to the ranks that
    own the corresponding tokens.  x here holds the tokens this rank
    RECEIVED, so global_count (not local_count) describes its rows."""
    xt = _t(x)
    _check_counts(xt, global_count, "global_gather")
    return _routed_all_to_all("global_gather", xt, group)


def get_cluster_from_args(args, selected_gpus=None):  # pragma: no cover
    """Launcher helper parity (reference utils.get_cluster_from_args);
    endpoint planning lives in distributed.launch here."""
    raise NotImplementedError(
        "use paddle_tpu.distributed.launch (python -m "
        "paddle_tpu.distributed.launch) for process planning")
