"""init_parallel_env + DataParallel (reference:
python/paddle/distributed/parallel.py:91, fluid/dygraph/parallel.py).

Under SPMD, DataParallel is free: batch sharded on the dp axis makes XLA
insert the gradient all-reduce (the reference's Reducer bucketing,
imperative/reducer.cc, becomes a compiler decision).  The wrapper below keeps
the reference API: it annotates inputs/parameters and otherwise passes
through.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .env import ParallelEnv, get_rank, get_world_size
from .mesh import HybridCommunicateGroup, fleet_mesh, get_mesh

_BOOTSTRAP_STORE = None  # rendezvous TCPStore, alive for the process


def init_parallel_env():
    """Bootstrap the parallel environment.

    Multi-process: rendezvous over our native TCPStore first (the
    reference's flow — parallel.py:236 builds a TCPStore, then the process
    group, reference python/paddle/distributed/parallel.py:91), exchanging
    the coordinator address through the store; then
    jax.distributed.initialize joins the processes into one
    multi-controller runtime, after which eager collectives
    (distributed.all_reduce etc.) execute across OS processes."""
    import os

    env = ParallelEnv()
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
    if eps and len(eps.split(",")) > 1:
        import jax

        from .store import TCPStore

        world = len(eps.split(","))
        master_host, master_port = eps.split(",")[0].rsplit(":", 1)
        store = TCPStore(host=master_host, port=int(master_port),
                         is_master=env.rank == 0, world_size=world)
        if env.rank == 0:
            coord = f"{master_host}:{int(master_port) + 1}"
            store.set("jax_coordinator", coord)
        else:
            coord = store.get("jax_coordinator").decode()
        try:
            from . import bootstrap

            # bootstrap selects gloo TCP collectives before the CPU
            # backend exists (without it every cross-process computation
            # dies with "Multiprocess computations aren't implemented on
            # the CPU backend") and guards re-entry.
            bootstrap.initialize_cluster(
                coordinator=coord, num_processes=world,
                process_id=env.rank)
        except (RuntimeError, ValueError) as e:
            if "already" not in str(e).lower():
                raise  # only an already-initialized runtime is benign
        global _BOOTSTRAP_STORE
        _BOOTSTRAP_STORE = store  # module-level ref: rank 0's server (and
        # every rank's client) must outlive this call for later barriers/
        # key exchange; a local would be GC'd at return
    if get_mesh() is None:
        fleet_mesh(dp_degree=1)
        HybridCommunicateGroup()
    return env


class DataParallel(Layer):
    """paddle.DataParallel wrapper: under the mesh, gradients reduce via
    GSPMD when the step is compiled; the wrapper exists for API parity and
    eager single-chip correctness (identity)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()
