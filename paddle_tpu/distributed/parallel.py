"""init_parallel_env + DataParallel (reference:
python/paddle/distributed/parallel.py:91, fluid/dygraph/parallel.py).

Under SPMD, DataParallel is free: batch sharded on the dp axis makes XLA
insert the gradient all-reduce (the reference's Reducer bucketing,
imperative/reducer.cc, becomes a compiler decision).  The wrapper below keeps
the reference API: it annotates inputs/parameters and otherwise passes
through.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .env import ParallelEnv, get_rank, get_world_size
from .mesh import HybridCommunicateGroup, fleet_mesh, get_mesh


def init_parallel_env():
    """Bootstrap the parallel environment.  Multi-host rendezvous (the
    reference's TCPStore + NCCL-id exchange) is handled by
    jax.distributed.initialize when PADDLE_TRAINER_ENDPOINTS is set."""
    import os

    env = ParallelEnv()
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
    if eps and len(eps.split(",")) > 1:
        import jax

        coord = eps.split(",")[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=len(eps.split(",")),
                process_id=env.rank)
        except (RuntimeError, ValueError):
            pass  # already initialized
    if get_mesh() is None:
        fleet_mesh(dp_degree=1)
        HybridCommunicateGroup()
    return env


class DataParallel(Layer):
    """paddle.DataParallel wrapper: under the mesh, gradients reduce via
    GSPMD when the step is compiled; the wrapper exists for API parity and
    eager single-chip correctness (identity)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()
