"""Distributed checkpointing — async, sharded, resharding-capable.

Reference: paddle.save/load pickles (framework/io.py), sharded save
(distributed/sharding/group_sharded.py:181 gathers slices to rank0), and
auto_parallel converter.py (manual cross-mesh reshard).  TPU-native: orbax
writes each shard from the host that owns it (OCDBT/tensorstore), restore
reshards automatically to the current mesh — checkpoints are
mesh-topology-independent by construction.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..core.tensor import Tensor


def _to_arrays(state_dict: Dict[str, Any]):
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = v._value
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


def save_state_dict(state_dict, path, async_save=False):
    """Sharded save via orbax; falls back to pickle when orbax is absent.

    The fallback commits ATOMICALLY (temp file + ``os.replace``): a
    crash mid-save must never destroy the previous checkpoint at
    ``path`` — the torn-save half of the resilience fault model
    (README "Resilience"; orbax gets the same property from its own
    commit-marker protocol).

    Multi-process discipline: orbax already writes each shard from the
    host that owns it and commits from one host.  The pickle fallback
    writes the FULL state, so under ``process_count() > 1`` only
    process 0 commits it (every host clobbering the same ``path`` over
    shared storage is the classic manifest-corruption race — hazard
    H113); the other processes barrier until the commit lands."""
    try:
        import orbax.checkpoint as ocp

        ckpter = ocp.StandardCheckpointer()
        ckpter.save(os.path.abspath(path), _to_arrays(state_dict), force=True)
        if not async_save:
            ckpter.wait_until_finished()
        return
    except ImportError:
        from ..framework.io import save as fsave
        from . import bootstrap

        ctx = bootstrap.cluster_context()
        if ctx.is_coordinator:
            tmp = f"{path}.tmp-p{ctx.index}-{os.getpid()}"
            try:
                fsave(state_dict, tmp)
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        ctx.barrier(f"save_state_dict:{os.path.basename(str(path))}")


def load_state_dict(path, target_state_dict=None):
    """Restore; when target_state_dict is given, arrays restore directly into
    the target's shardings (cross-mesh resharding for free)."""
    try:
        import orbax.checkpoint as ocp

        ckpter = ocp.StandardCheckpointer()
        if target_state_dict is not None:
            template = jax.tree_util.tree_map(
                lambda v: v._value if isinstance(v, Tensor) else v,
                target_state_dict,
                is_leaf=lambda x: isinstance(x, Tensor))
            restored = ckpter.restore(os.path.abspath(path), template)
        else:
            restored = ckpter.restore(os.path.abspath(path))
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if hasattr(v, "shape") else v, restored)
    except ImportError:
        from ..framework.io import load as fload

        return fload(path)


class AsyncCheckpointer:
    """Background checkpoint writer (the reference has no async save; hapi
    callbacks block).  Keeps at most `max_to_keep` checkpoints.

    Multi-process discipline is orbax's: CheckpointManager must be
    constructed on EVERY process of the fleet (it coordinates its own
    per-process writes + barriers internally) — do not wrap calls in an
    ``is_coordinator`` gate.  For the in-tree equivalent without the
    orbax dependency, use ``resilience.ResilientCheckpointer`` — it
    auto-switches to the sharded elastic protocol under
    ``jax.distributed`` (README: Elastic multi-host checkpointing)."""

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 enable_async_checkpointing=True))

    def save(self, step, state_dict):
        import orbax.checkpoint as ocp

        self.manager.save(step, args=ocp.args.StandardSave(
            _to_arrays(state_dict)))

    def restore_latest(self, template_state=None):
        """Restore the newest checkpoint that actually LOADS, walking
        older steps when the latest is unreadable or corrupt (truncated
        shards, missing metadata) instead of raising — a crash must not
        strand a run behind its own torn checkpoint.  Returns
        ``(None, None)`` when no step restores."""
        import sys

        import orbax.checkpoint as ocp

        template = _to_arrays(template_state) \
            if template_state is not None else None
        for step in sorted(self.manager.all_steps(), reverse=True):
            try:
                if template is not None:
                    restored = self.manager.restore(
                        step, args=ocp.args.StandardRestore(template))
                else:
                    restored = self.manager.restore(step)
            except Exception as e:  # noqa: BLE001 — any unreadable step
                print(f"[paddle_tpu.distributed.checkpoint] skipping "
                      f"unreadable checkpoint step {step}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                continue
            wrapped = jax.tree_util.tree_map(
                lambda v: Tensor(v) if hasattr(v, "shape") else v, restored)
            return step, wrapped
        return None, None

    def wait(self):
        self.manager.wait_until_finished()
