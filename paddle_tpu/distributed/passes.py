"""paddle.distributed.passes (reference:
python/paddle/distributed/passes/pass_base.py — new_pass / PassManager /
PassContext over the distributed-training pass registry).

One pass framework, two entry points: these objects front the SAME
registry as ``paddle_tpu.static.passes`` (register_pass/apply_pass); the
reference keeps a second C++ registry for its fleet passes, which this
design collapses — a pass here is a Python function over Program blocks,
and anything device-level (fusion, layout, collectives) belongs to XLA.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..static import passes as _p

__all__ = ["new_pass", "PassManager", "PassContext", "PassBase"]


class PassContext:
    """Carries cross-pass state (reference PassContext.attrs)."""

    def __init__(self):
        self._attrs: Dict = {}
        self._applied: List[str] = []

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    """A named, parameterized pass handle (reference PassBase: check_before
    / apply).  ``apply`` mutates the given programs in place and returns
    the context, recording per-program change counts in its attrs."""

    def __init__(self, name: str, attrs: Optional[Dict] = None):
        # resolve eagerly so a typo fails at new_pass() time, like the
        # reference's registry lookup
        self._fn = _p.get_pass(name)
        self.name = name
        self.attrs = dict(attrs or {})

    def _check_self(self) -> bool:
        return True

    def apply(self, main_programs, startup_programs=None,
              context: Optional[PassContext] = None) -> PassContext:
        context = context or PassContext()
        if not isinstance(main_programs, (list, tuple)):
            main_programs = [main_programs]
        changed = 0
        for prog in main_programs:
            changed += _p.apply_pass(prog, self.name, **self.attrs)
        context._applied.append(self.name)
        context.set_attr(f"{self.name}.num_changed", changed)
        return context


def new_pass(name: str, pass_attrs: Optional[Dict] = None) -> PassBase:
    """reference pass_base.py new_pass(name, pass_attrs)."""
    return PassBase(name, pass_attrs)


class PassManager:
    """Ordered pass application (reference PassManager: conflict-aware
    _apply_impl; ordering here is exactly the list the user gives)."""

    def __init__(self, passes: List[PassBase]):
        self._passes = [p if isinstance(p, PassBase) else new_pass(str(p))
                        for p in passes]
        self._context = PassContext()

    @property
    def context(self) -> PassContext:
        return self._context

    @property
    def names(self) -> List[str]:
        return [p.name for p in self._passes]

    def apply(self, main_programs, startup_programs=None) -> PassContext:
        for p in self._passes:
            p.apply(main_programs, startup_programs, self._context)
        return self._context
