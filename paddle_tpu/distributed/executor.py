"""Runtime SPMD mesh execution — the runtime half of the shard plan.

``analysis/shardplan.py`` (PR 7) de-risked mesh sharding *statically*:
it propagates the frozen llama ``SpecLayout`` through the traced
train/decode/prefill jaxprs on an abstract mesh and prices every
implied collective.  This module executes those same steps as one
GSPMD program per step over a real ``jax.sharding.Mesh``:

- ``MeshExecutor({"data": 2, "fsdp": 2, "tp": 2})`` builds the mesh —
  from real TPU devices, or on CPU from forced host devices
  (``XLA_FLAGS=--xla_force_host_platform_device_count=8``) so tier-1
  covers every code path.  When the host has fewer devices than the
  axes need, it degrades to an all-ones mesh instead of failing.
- ``install(model)`` lays out params, optimizer slots (inheriting each
  param's spec, same id-matching as shardplan), batch, and RNG with
  ``NamedSharding``s and arranges for the hapi train step to be jitted
  with explicit in_shardings + donation (donation pins the state
  *outputs* to the same layout, so steady-state steps never reshard).
- ``install_serving(model, pool)`` does the same for the serving
  engine: weights sharded in place (the decode/prefill steps capture
  them as committed jit constants) and the paged KV pool laid out
  ``PS(None, None, "tp", None)``.
- ``reconcile_train`` / ``reconcile_serving`` cross-check the COMPILED
  programs against the static ``PlanReport`` — collective footprint,
  per-device memory, and realized output shard shapes — surfacing any
  divergence as diagnostic **S209** (runtime-vs-plan mismatch).  Zero
  S209s means the bytes on the wire are the bytes the plan priced.
"""
from __future__ import annotations

import re
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .sharding import SpecLayout, get_sharding_spec

__all__ = [
    "MeshExecutor",
    "as_executor",
    "current_executor",
    "active_mesh",
    "active_mesh_axes",
    "default_shardplan_mesh",
]

S209 = "S209"

# the process-wide executor registry: sharding helpers
# (distributed/sharding.py) and tools/lint_tpu.py --shardplan fall back
# to the registered executor's mesh when no mesh is passed explicitly
_ACTIVE: Optional["MeshExecutor"] = None


def current_executor() -> Optional["MeshExecutor"]:
    return _ACTIVE


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE.mesh if _ACTIVE is not None else None


def active_mesh_axes() -> Optional[Dict[str, int]]:
    return dict(_ACTIVE.axes) if _ACTIVE is not None else None


def default_shardplan_mesh() -> Optional[Dict[str, int]]:
    """The registered executor's axes, for CI audits of the mesh
    actually in use (``lint_tpu.py --shardplan`` default)."""
    return active_mesh_axes()


def as_executor(mesh) -> "MeshExecutor":
    """Coerce an ``{axis: size}`` dict / ``jax.sharding.Mesh`` /
    ``MeshExecutor`` into a ``MeshExecutor``."""
    if isinstance(mesh, MeshExecutor):
        return mesh
    if isinstance(mesh, Mesh):
        return MeshExecutor(dict(mesh.shape),
                            devices=list(mesh.devices.flat))
    if isinstance(mesh, dict):
        return MeshExecutor(mesh)
    raise TypeError(
        f"mesh must be an axis dict, jax.sharding.Mesh, or MeshExecutor, "
        f"got {type(mesh).__name__}")


def _entry_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")


def _hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    """Count collective ops in optimized HLO text (op applications only:
    the op name immediately followed by '(' — instruction *names* carry
    a '.N' suffix and never match)."""
    counts: Dict[str, int] = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        kind = m.group(1).replace("-", "_")
        counts[kind] = counts.get(kind, 0) + 1
    return counts


class MeshExecutor:
    """Lays out state on a named device mesh and runs each registered
    step as one GSPMD program, validated against the static shard plan.

    Parameters
    ----------
    axes: ``{axis_name: size}`` in mesh-major order, e.g.
        ``{"data": 2, "fsdp": 2, "tp": 2}``.
    layout: the ``SpecLayout`` mapping parameter roles to
        ``PartitionSpec``s (default: the canonical llama layout).
    devices: explicit device list (default ``jax.devices()``).
    register: make this the process-wide executor that sharding
        helpers and ``--shardplan`` fall back to.
    """

    def __init__(self, axes: Dict[str, int], *, layout: SpecLayout = None,
                 devices: Sequence[Any] = None, register: bool = True,
                 topology=None):
        names = list(axes)
        sizes = [int(axes[k]) for k in names]
        if not names or any(s < 1 for s in sizes):
            raise ValueError(f"invalid mesh axes {axes!r}")
        devs = list(devices) if devices is not None else list(jax.devices())
        need = int(np.prod(sizes))
        self.degraded = False
        if need > len(devs):
            hint = ""
            if devs and devs[0].platform == "cpu":
                hint = (" (set XLA_FLAGS=--xla_force_host_platform_"
                        "device_count=N to emulate an N-device host)")
            warnings.warn(
                f"mesh {dict(zip(names, sizes))} needs {need} devices but "
                f"only {len(devs)} are visible{hint}; degrading to a "
                f"single-device {dict.fromkeys(names, 1)} mesh")
            sizes = [1] * len(names)
            need = 1
            self.degraded = True
        self.mesh = Mesh(
            np.asarray(devs[:need]).reshape(sizes), tuple(names))
        self.axes: Dict[str, int] = dict(zip(names, sizes))
        # true when the mesh's devices span >1 process (the bootstrap's
        # multi-controller runtime): host values then commit via
        # make_array_from_callback and S209 audits aggregate per-process
        self.multiprocess = len(
            {getattr(d, "process_index", 0) for d in self.mesh.devices.flat}
        ) > 1
        self.layout = layout if layout is not None else SpecLayout()
        # analysis.Topology: makes every shard plan this executor
        # requests price host-spanning collectives at DCN rates; the
        # reconcile_* entry points then refuse to bless a single-host
        # runtime against a multi-host-priced plan
        self.topology = topology
        self.reports: Dict[str, Tuple[Any, List[Any]]] = {}
        self._replicated = NamedSharding(self.mesh, PartitionSpec())
        if register:
            global _ACTIVE
            _ACTIVE = self
        self._export_gauges()

    # ----- layout primitives -------------------------------------------
    def clean_spec(self, spec, shape=None) -> PartitionSpec:
        """Restrict a PartitionSpec to this mesh: drop entries naming
        absent axes and entries whose axis product does not divide the
        dim (mirrors shardplan's ``_drop_indivisible``)."""
        entries = list(spec) if spec is not None else []
        out: List[Any] = []
        for dim, entry in enumerate(entries):
            axes = _entry_axes(entry)
            if not axes or any(a not in self.mesh.shape for a in axes):
                out.append(None)
                continue
            n = 1
            for a in axes:
                n *= int(self.mesh.shape[a])
            if shape is not None and (
                    dim >= len(shape) or int(shape[dim]) % n != 0):
                out.append(None)
                continue
            out.append(tuple(axes) if len(axes) > 1 else axes[0])
        if shape is not None:
            out = out[:len(shape)]
        while out and out[-1] is None:
            out.pop()
        return PartitionSpec(*out)

    def sharding(self, spec=None, shape=None) -> NamedSharding:
        if spec is None:
            return self._replicated
        return NamedSharding(self.mesh, self.clean_spec(spec, shape))

    @property
    def replicated(self) -> NamedSharding:
        return self._replicated

    def shard_shape(self, shape, spec) -> Tuple[int, ...]:
        """Per-device shard shape of ``shape`` under ``spec``."""
        spec = self.clean_spec(spec, shape)
        entries = list(spec) + [None] * (len(shape) - len(list(spec)))
        out = []
        for dim, entry in zip(shape, entries):
            n = 1
            for a in _entry_axes(entry):
                n *= int(self.mesh.shape[a])
            out.append(int(dim) // n)
        return tuple(out)

    def put(self, value, spec=None, shape=None):
        """Commit an array (or Tensor ``_value``) onto the mesh.  Under
        tracing, apply a sharding constraint instead.  When the mesh
        spans processes, every process passes the same GLOBAL host value
        and receives its addressable slice of the distributed array."""
        if shape is None:
            shape = tuple(np.shape(value))
        sh = self.sharding(spec, shape)
        if hasattr(value, "aval") and not hasattr(value,
                                                  "addressable_shards"):
            return jax.lax.with_sharding_constraint(value, sh)
        if self.multiprocess and not hasattr(value, "addressable_shards"):
            host = np.asarray(value)
            return jax.make_array_from_callback(
                tuple(shape), sh, lambda idx: host[idx])
        return jax.device_put(value, sh)

    def fetch(self, value) -> np.ndarray:
        """Host numpy view of a step output under any topology:
        fully-addressable (single-process) arrays read directly; a
        multi-process array reads via its local shards when replicated,
        else through an allgather — so callers never trip the
        'non-addressable array' fetch guard."""
        if isinstance(value, Tensor):
            value = value._value
        if getattr(value, "is_fully_addressable", True) or \
                getattr(value, "is_fully_replicated", False):
            return np.asarray(value)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(
            value, tiled=True))

    # ----- state layout ------------------------------------------------
    def shard_params(self, layer) -> int:
        """Lay out every parameter per its role spec (buffers stay
        replicated) and stamp ``_sharding_spec`` so optimizer-slot
        creation and the jit in_shardings can inherit it."""
        n = 0
        for name, p in layer.named_parameters():
            shape = tuple(np.shape(p._value))
            spec = self.clean_spec(self.layout.param_spec(name), shape)
            p._value = self.put(p._value, spec, shape)
            p._sharding_spec = spec
            n += 1
        for _, b in layer.named_buffers():
            b._value = self.put(b._value, PartitionSpec())
        return n

    def _slot_sharding(self, arr, param) -> NamedSharding:
        """A slot inherits its param's spec iff shapes match (same
        id-matching rule as shardplan); scalars etc. stay replicated."""
        shape = tuple(np.shape(arr))
        if param is not None and shape == tuple(param.shape):
            spec = get_sharding_spec(param)
            if spec is not None:
                return self.sharding(spec, shape)
        return self._replicated

    def install_optimizer(self, opt) -> None:
        """Hook ``_add_accumulator`` so slots materialize directly on
        their param's layout, and pin any existing slots."""
        if getattr(opt, "_mesh_executor", None) is self:
            return
        opt._mesh_executor = self
        ex = self
        orig_add = opt._add_accumulator

        def _add_accumulator(name, param, **kw):
            arr = orig_add(name, param, **kw)
            sh = ex._slot_sharding(arr, param)
            try:
                if hasattr(arr, "aval") and not hasattr(
                        arr, "addressable_shards"):
                    arr = jax.lax.with_sharding_constraint(arr, sh)
                else:
                    arr = jax.device_put(arr, sh)
                opt._accumulators[name][id(param)] = arr
            except Exception:  # noqa: BLE001 — layout is best-effort
                pass
            return arr

        opt._add_accumulator = _add_accumulator
        self.reshard_optimizer(opt)

    def reshard_optimizer(self, opt) -> None:
        params = {}
        for entry in (getattr(opt, "_parameter_list", None) or ()):
            group = (entry.get("params", []) if isinstance(entry, dict)
                     else [entry])
            for p in group:
                if isinstance(p, Tensor):
                    params[id(p)] = p
        for name, store in getattr(opt, "_accumulators", {}).items():
            for pid, arr in list(store.items()):
                if hasattr(arr, "aval") and not hasattr(
                        arr, "addressable_shards"):
                    continue  # mid-trace slot: leave it to the program
                store[pid] = jax.device_put(
                    arr, self._slot_sharding(arr, params.get(pid)))

    def install(self, model) -> "MeshExecutor":
        """Wire a prepared ``hapi.Model`` for mesh execution: shard its
        params and slots, and bind this executor to the compiled
        train/eval steps so they jit with explicit in_shardings."""
        net = getattr(model, "network", model)
        self.shard_params(net)
        opt = getattr(model, "_optimizer", None)
        if opt is not None:
            self.install_optimizer(opt)
        for attr in ("_train_step_fn", "_eval_step_fn"):
            fn = getattr(model, attr, None)
            if fn is None:
                continue
            sfn = getattr(fn, "_fn", fn)  # unwrap compile_tracker
            if hasattr(sfn, "_cache"):
                sfn._mesh_executor = self
        model._mesh_executor = self
        net._mesh_executor = self
        return self

    def reshard(self, network, optimizer=None) -> None:
        """Re-lay-out after a host-side state load (checkpoint restore
        rebinds ``_value`` to host arrays)."""
        self.shard_params(network)
        if optimizer is not None:
            self.reshard_optimizer(optimizer)

    # ----- jit integration ---------------------------------------------
    def cache_token(self):
        """Part of the StaticFunction cache key: a mesh change must
        select/build a different executable."""
        return (tuple(self.axes.items()), id(self.mesh))

    def train_in_shardings(self, state, dyn_vals):
        """Explicit in_shardings for the hapi step's flattened invars
        ``(state_vals, dyn_vals, lrs, rng_key)``: params by role spec,
        buffers replicated, slots inheriting their param (id-matched),
        batch leaves on the batch spec, lr/rng replicated.  With
        ``donate_argnums=(0,)`` XLA pins the state *outputs* to the same
        layout — steady-state steps never reshard."""
        state_sh: List[NamedSharding] = []
        for p in state.params:
            state_sh.append(self.sharding(
                get_sharding_spec(p), tuple(np.shape(p._value))))
        for _b in state.buffers:
            state_sh.append(self._replicated)
        by_id = {id(p): p for p in state.params}
        for store, key in state.opt_slots():
            state_sh.append(self._slot_sharding(store[key], by_id.get(key)))
        batch = self.layout.batch_spec()
        dyn_sh = [self.sharding(batch, tuple(np.shape(v)))
                  for v in dyn_vals]
        return (state_sh, dyn_sh, self._replicated, self._replicated)

    def constrain_state_outputs(self, state, new_state, slot_handles):
        """Pin a traced step's state outputs to the planned layout
        (params by role spec, buffers replicated, slots inheriting their
        param).  Called inside ``jit.to_static``'s traced body: without
        it XLA's propagation-to-output may reshard state between steps
        and the next call's committed args mismatch in_shardings."""
        n_p, n_b = len(state.params), len(state.buffers)
        by_id = {id(p): p for p in state.params}
        out = list(new_state)
        for i, p in enumerate(state.params):
            sh = self.sharding(get_sharding_spec(p),
                               tuple(np.shape(out[i])))
            out[i] = jax.lax.with_sharding_constraint(out[i], sh)
        for i in range(n_p, n_p + n_b):
            out[i] = jax.lax.with_sharding_constraint(
                out[i], self._replicated)
        for j, (_store, key) in enumerate(slot_handles):
            i = n_p + n_b + j
            if i >= len(out):
                break
            sh = self._slot_sharding(out[i], by_id.get(key))
            out[i] = jax.lax.with_sharding_constraint(out[i], sh)
        return out

    def shard_batch(self, values):
        """Commit host batch leaves onto the batch spec (matching the
        step's in_shardings, so dispatch never reshards)."""
        spec = self.layout.batch_spec()
        out = []
        for v in values:
            if isinstance(v, Tensor):
                v._value = self.put(v._value, spec)
                out.append(v)
            elif v is not None and hasattr(v, "shape"):
                out.append(self.put(v, spec))
            else:
                out.append(v)
        return out

    # ----- serving -----------------------------------------------------
    def kv_pool_spec(self) -> PartitionSpec:
        # [num_blocks, block_size, kv_heads, head_dim] — heads on tp
        return PartitionSpec(None, None, self.layout.tp_axis, None)

    def static_kv_spec(self) -> PartitionSpec:
        """Sequential ``generate()`` StaticKVCache layout,
        [batch, max_len, kv_heads, head_dim] — kv heads on tp, matching
        the paged pool (``kv_pool_spec``) so the one-shot path stops
        replicating a full max_len cache per chip."""
        return PartitionSpec(None, None, self.layout.tp_axis, None)

    def shard_kv_layers(self, layers):
        spec = self.kv_pool_spec()
        # Quantized pools carry (k, v, k_scale, v_scale); the per-row
        # scale sidecars [num_blocks, block_size] have no kv-head axis
        # to shard, so they replicate.
        scale_spec = PartitionSpec(None, None)
        out = []
        for entry in layers:
            k, v = entry[0], entry[1]
            sharded = (self.put(k, spec), self.put(v, spec))
            if len(entry) == 4:
                sharded += (self.put(entry[2], scale_spec),
                            self.put(entry[3], scale_spec))
            out.append(sharded)
        return out

    def install_serving(self, model, pool) -> "MeshExecutor":
        """Shard the serving model + paged KV pool.  Must run BEFORE the
        decode/prefill step makers: the steps capture the weights as jit
        constants, so rebinding ``_value`` here is what makes the
        compiled programs SPMD."""
        self.shard_params(model)
        pool.layers = self.shard_kv_layers(pool.layers)
        model._mesh_executor = self
        return self

    # ----- observability -----------------------------------------------
    def _export_gauges(self) -> None:
        from .. import observability

        if not observability.enabled():
            return
        reg = observability.get_registry()
        reg.gauge("mesh_num_devices",
                  "devices in the executor's mesh").set(int(self.mesh.size))
        g = reg.gauge("mesh_axis_sizes",
                      "per-axis size of the executor's mesh")
        for ax, sz in self.axes.items():
            g.set(int(sz), axis=ax)
        reg.gauge("mesh_process_span",
                  "distinct processes owning this mesh's devices").set(
            len({getattr(d, "process_index", 0)
                 for d in self.mesh.devices.flat}))

    # ----- S209 reconciliation -----------------------------------------
    def _plan_request(self):
        from ..analysis import shardplan as _shardplan

        return _shardplan.PlanRequest(mesh=dict(self.axes),
                                      layout=self.layout,
                                      raise_on_error=False,
                                      topology=self.topology)

    def _check_plan_topology(self, plan) -> None:
        """A plan priced for a multi-host Topology cannot be reconciled
        against a single-host runtime: the DCN phases it prices do not
        exist on this mesh, so S209 'agreement' would be meaningless.
        Raise instead of silently blessing the wrong fleet shape."""
        topo = getattr(plan, "topology", None)
        if topo is None or int(topo.hosts) <= 1:
            return
        procs = jax.process_count()
        if procs < int(topo.hosts):
            raise RuntimeError(
                f"shard plan was priced for a {topo.hosts}-host topology "
                f"({topo.hosts} × {topo.chips_per_host_count} chips) but "
                f"this runtime spans {procs} process(es) over "
                f"{self.mesh.size} device(s) — the DCN collective phases "
                "the plan prices cannot exist on a single-host mesh; "
                f"launch under jax.distributed with {topo.hosts} "
                "processes, or drop `topology` from the MeshExecutor / "
                "PlanRequest to reconcile a single-host plan")

    def _reconcile_compiled(self, plan, compiled, *, name,
                            trailing_out_expect=None):
        """Compare one compiled program against its static PlanReport.
        Returns S209 diagnostics; an empty list means reconciled."""
        from ..analysis.verifier import Diagnostic, ERROR, WARNING

        diags: List[Any] = []
        hlo = ""
        try:
            hlo = compiled.as_text()
        except Exception:  # noqa: BLE001 — backend may not expose HLO
            pass
        if hlo and self.mesh.size > 1:
            counts = _hlo_collective_counts(hlo)
            n_run = sum(counts.values())
            if plan.comm_bytes > 0 and n_run == 0:
                diags.append(Diagnostic(
                    S209, ERROR,
                    f"static plan prices {len(plan.collectives)} "
                    f"collective(s) ({plan.comm_bytes / 2**10:.1f} KiB on "
                    "the wire) but the compiled HLO contains none — the "
                    "step is running single-device math; the input "
                    "shardings did not take", name))
            elif plan.comm_bytes == 0 and n_run > 0:
                diags.append(Diagnostic(
                    S209, WARNING,
                    f"compiled HLO contains {n_run} collective op(s) "
                    f"({counts}) where the plan prices zero bytes — the "
                    "runtime communicates off-plan", name))
        try:
            ma = compiled.memory_analysis()
            run_bytes = int(ma.argument_size_in_bytes
                            + ma.output_size_in_bytes
                            + ma.temp_size_in_bytes)
        except Exception:  # noqa: BLE001 — Unimplemented on some backends
            run_bytes = None
        if run_bytes is not None and plan.per_chip_peak_hbm_bytes > 0:
            # generous bound: the plan's peak is LIVE bytes; the compiled
            # footprint counts whole buffers — only a multiple signals a
            # layout that silently replicated what the plan sharded
            budget = 4 * int(plan.per_chip_peak_hbm_bytes) + (64 << 20)
            if run_bytes > budget:
                diags.append(Diagnostic(
                    S209, WARNING,
                    f"compiled per-device footprint {run_bytes / 2**20:.1f}"
                    f" MiB exceeds 4x the planned per-chip peak "
                    f"({plan.per_chip_peak_hbm_bytes / 2**20:.1f} MiB) + "
                    "64 MiB slack — state may be replicated instead of "
                    "sharded", name))
        if trailing_out_expect:
            try:
                outs = jax.tree_util.tree_leaves(compiled.output_shardings)
            except Exception:  # noqa: BLE001
                outs = []
            n = len(trailing_out_expect)
            tail = outs[-n:] if len(outs) >= n else []
            for (label, shape, spec), sh in zip(trailing_out_expect, tail):
                want = self.shard_shape(shape, spec)
                try:
                    got = tuple(sh.shard_shape(tuple(shape)))
                except Exception:  # noqa: BLE001 — opaque sharding repr
                    continue
                if got != want:
                    diags.append(Diagnostic(
                        S209, ERROR,
                        f"{label}: compiled output shard {got} != planned "
                        f"{want} under spec {spec} — the realized layout "
                        "diverges from the shard plan", name))
        return diags

    def reconcile_train(self, model, inputs, labels):
        """Cross-check the compiled hapi train step against the static
        plan.  Needs at least one executed train batch (the compiled
        steady-state entry is what gets audited).  Returns
        ``(PlanReport, [S209 diagnostics])``."""
        plan = model.shardplan(inputs, labels, request=self._plan_request())
        self._check_plan_topology(plan)
        fn = model._train_step_fn
        sfn = getattr(fn, "_fn", fn)
        entries = [e for e in sfn._cache.values()
                   if getattr(e, "_compiled", None) is not None]
        if not entries:
            raise RuntimeError(
                "reconcile_train needs a compiled train step — run at "
                "least one train batch first")
        entry = entries[-1]
        state = sfn._state
        names: Dict[int, str] = {}
        for layer in (sfn._layers or ()):
            for nm, p in layer.named_parameters():
                names.setdefault(id(p), nm)
        by_id = {id(p): p for p in state.params}
        expect: List[Tuple[str, Tuple[int, ...], PartitionSpec]] = []
        for p in state.params:
            nm = names.get(id(p), "param")
            shape = tuple(np.shape(p._value))
            expect.append(
                (nm, shape,
                 self.clean_spec(self.layout.param_spec(nm), shape)))
        for b in state.buffers:
            expect.append(("buffer", tuple(np.shape(b._value)),
                           PartitionSpec()))
        for store, key in state.opt_slots():
            arr = store[key]
            shape = tuple(np.shape(arr))
            p = by_id.get(key)
            spec = PartitionSpec()
            if p is not None and shape == tuple(p.shape):
                spec = self.clean_spec(
                    self.layout.param_spec(names.get(id(p), "param")),
                    shape)
            expect.append((f"slot[{names.get(key, 'global')}]", shape,
                           spec))
        diags = self._reconcile_compiled(
            plan, entry._compiled, name="hapi::train_step",
            trailing_out_expect=expect)
        diags = self._aggregate_process_diags(
            "hapi::train_step", entry._compiled, diags)
        self.reports["hapi::train_step"] = (plan, diags)
        return plan, diags

    def _aggregate_process_diags(self, name, compiled, diags):
        """S209 across the process boundary: every process audits its
        OWN compiled program; process 0's aggregation is an allgather of
        each process's (diag count, collective-footprint fingerprint).
        In a healthy SPMD fleet the rows are identical — a divergent row
        means some host compiled different collectives than its peers
        (skew in code, flags, or device slices), which no single-process
        audit can see."""
        if not self.multiprocess or jax.process_count() <= 1:
            return diags
        import json as _json
        import zlib

        from jax.experimental import multihost_utils

        from ..analysis.verifier import Diagnostic, ERROR

        hlo = ""
        try:
            hlo = compiled.as_text()
        except Exception:  # noqa: BLE001
            pass
        counts = _hlo_collective_counts(hlo) if hlo else {}
        fp = zlib.crc32(_json.dumps(sorted(counts.items())).encode()
                        ) & 0x7FFFFFFF
        row = np.array([len(diags), fp], dtype=np.int32)
        rows = np.asarray(multihost_utils.process_allgather(row))
        if not bool((rows == rows[0]).all()):
            # identical on every process (allgather), so the fleet
            # agrees on the verdict even though process 0 reports it
            diags.append(Diagnostic(
                S209, ERROR,
                f"processes disagree on the compiled step: per-process "
                f"(n_diags, collective_fingerprint) rows {rows.tolist()} "
                "— some host is running a divergent program", name))
        return diags

    def _serving_sds(self, arg, spec):
        """Mirror shardplan's spec broadcasting over container args and
        attach shardings to the abstract ShapeDtypeStructs."""
        if isinstance(arg, (list, tuple)):
            nested = isinstance(spec, (list, tuple)) and not isinstance(
                spec, PartitionSpec)
            seq = [self._serving_sds(a, spec[i] if nested else spec)
                   for i, a in enumerate(arg)]
            return tuple(seq) if isinstance(arg, tuple) else seq
        shape = tuple(arg.shape)
        return jax.ShapeDtypeStruct(
            shape, arg.dtype, sharding=self.sharding(spec, shape))

    def reconcile_serving(self, engine):
        """Cross-check the serving decode + prefill steps.  AOT-compiles
        each step from sharded abstract args (bypassing the retrace
        guard, so compile counters are untouched) and reconciles against
        its PlanReport.  Returns ``{step_name: (plan, diags)}``."""
        from ..analysis import shardplan as _shardplan
        from ..analysis import xray as _xray

        cfg = engine.config
        model = engine.model
        decode_args, prefill_args = _xray._serving_abstract_args(
            model, batch=cfg.max_batch_size, num_blocks=cfg.num_blocks,
            block_size=cfg.block_size,
            max_blocks_per_seq=engine.max_blocks_per_seq,
            chunk_tokens=engine.chunk_tokens)
        decode_specs, prefill_specs = _shardplan._serving_arg_specs(
            model, self.layout, decode_args, prefill_args)
        req = self._plan_request()
        out: Dict[str, Tuple[Any, List[Any]]] = {}
        for name, step, args, specs, data_leaves in (
                ("serving::decode_step", engine._decode_step,
                 decode_args, decode_specs, (("tokens", 0),)),
                ("serving::prefill_step", engine._prefill_step,
                 prefill_args, prefill_specs, (("chunk_ids", 0),))):
            plan = _shardplan.plan_step(
                step, args, model=model, arg_specs=specs, request=req,
                name=name, data_input_leaves=data_leaves,
                step_kind=("paged_decode" if "decode" in name
                           else "chunked_prefill"))
            self._check_plan_topology(plan)
            fn = step
            if hasattr(fn, "_fn") and hasattr(fn, "compiles"):
                fn = fn._fn
            sds = [self._serving_sds(a, s) for a, s in zip(args, specs)]
            compiled = fn.lower(*sds).compile()
            # both steps return (arrays, [(k, v) per layer]) — the pool
            # leaves are the trailing outputs and must come back on the
            # pool spec, or every decode step pays a reshard
            pool_spec = self.kv_pool_spec()
            expect = []
            for i, (k, v) in enumerate(args[1]):
                for tag, a in (("k", k), ("v", v)):
                    shape = tuple(a.shape)
                    expect.append((f"kv_pool[{i}].{tag}", shape,
                                   self.clean_spec(pool_spec, shape)))
            diags = self._reconcile_compiled(
                plan, compiled, name=name, trailing_out_expect=expect)
            self.reports[name] = (plan, diags)
            out[name] = (plan, diags)
        return out

    # ----- lifecycle ---------------------------------------------------
    def close(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "MeshExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        return (f"MeshExecutor({self.axes}, devices={self.mesh.size}, "
                f"degraded={self.degraded})")
