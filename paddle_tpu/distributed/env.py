"""Distributed environment (reference: python/paddle/distributed/parallel.py
ParallelEnv + fleet role makers reading PADDLE_TRAINER_ID/endpoints).

On TPU, rank/world come from the JAX multi-host runtime (jax.process_index /
process_count) with PADDLE_* env vars honored for launch-controller parity.
"""
from __future__ import annotations

import os

import jax


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        return int(env)
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", get_rank()))

    @property
    def device_id(self):
        return self.local_rank

    @property
    def nranks(self):
        return get_world_size()

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else [self.current_endpoint]
