"""Async multi-program driver (reference:
paddle/fluid/distributed/fleet_executor/ — FleetExecutor fleet_executor.h:35
runs a Carrier:49 of Interceptors:46 that stream InterceptorMessages
between per-stage TaskNodes over a MessageBus; used for pipeline and
distributed inference).

TPU-native scope: the heavy pipeline schedule compiles into ONE XLA
program here (distributed/pipeline.py pipeline_1f1b), so this driver
covers the part that design does not — running SEVERAL compiled programs
as a streaming DAG (multi-stage inference, producer/consumer graphs)
with host threads playing the interceptor loops and bounded queues
playing the message bus.  Each task node owns a compiled callable;
microbatches stream through with backpressure, so stage i+1 runs while
stage i works on the next microbatch (XLA dispatch is async, letting
device work overlap too).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence

_STOP = object()


class TaskNode:
    """One actor in the DAG (reference: task_node.h — a program slice +
    upstream/downstream ids).  ``fn`` maps one microbatch's inputs to
    outputs; multiple upstreams deliver their outputs as ordered args."""

    def __init__(self, fn: Callable, name: Optional[str] = None,
                 max_run_times: Optional[int] = None, buffer_size: int = 2):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "task")
        self.max_run_times = max_run_times
        self.buffer_size = max(1, int(buffer_size))
        self.upstream: List["TaskNode"] = []
        self.downstream: List["TaskNode"] = []

    def add_downstream_task(self, other: "TaskNode"):
        self.downstream.append(other)
        other.upstream.append(self)
        return other


class FleetExecutor:
    """Drive a TaskNode DAG over streaming microbatches.

    run(feeds) pushes each microbatch into the source nodes and returns
    the sink outputs in order.  Interceptor loops are daemon threads; the
    bounded queues give the reference's credit-based backpressure."""

    def __init__(self, task_nodes: Sequence[TaskNode]):
        self.nodes = list(task_nodes)
        names = [n.name for n in self.nodes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate task names: {names}")
        self.sources = [n for n in self.nodes if not n.upstream]
        self.sinks = [n for n in self.nodes if not n.downstream]
        if not self.sources or not self.sinks:
            raise ValueError("DAG needs at least one source and one sink")

    def run(self, feeds: Sequence, timeout: float = 120.0) -> List:
        """feeds: list of microbatch inputs for the source node(s).
        With several sources, each feed is a dict {source_name: value}."""
        in_queues: Dict[int, List[queue.Queue]] = {}
        for node in self.nodes:
            n_in = max(1, len(node.upstream))
            in_queues[id(node)] = [queue.Queue(maxsize=node.buffer_size)
                                   for _ in range(n_in)]
        sink_out: Dict[str, queue.Queue] = {
            n.name: queue.Queue() for n in self.sinks}
        errors: List[BaseException] = []

        # (downstream, slot) pairs per node, precomputed from the upstream
        # lists: upstream.index(node) would always resolve the FIRST slot
        # when a node feeds the same downstream twice, starving the second
        # input queue until the join timeout
        out_edges: Dict[int, List] = {id(n): [] for n in self.nodes}
        for d in self.nodes:
            for slot, u in enumerate(d.upstream):
                out_edges[id(u)].append((d, slot))

        def interceptor(node: TaskNode):
            qs = in_queues[id(node)]
            count = 0
            draining = False
            while True:
                vals = [q.get() for q in qs]
                if any(v is _STOP for v in vals):
                    break
                if draining:
                    continue  # dead node keeps CONSUMING so upstream
                    # puts never block (credit-based shutdown; without
                    # this a failed stage deadlocks the whole carrier)
                try:
                    out = node.fn(*vals)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    draining = True
                    continue
                count += 1
                if node.downstream:
                    for d, slot in out_edges[id(node)]:
                        in_queues[id(d)][slot].put(out)
                else:
                    sink_out[node.name].put(out)
                if node.max_run_times and count >= node.max_run_times:
                    draining = True
            # propagate shutdown downstream
            for d, slot in out_edges[id(node)]:
                in_queues[id(d)][slot].put(_STOP)

        threads = [threading.Thread(target=interceptor, args=(n,),
                                    daemon=True, name=f"interceptor-{n.name}")
                   for n in self.nodes]
        for t in threads:
            t.start()

        for feed in feeds:
            for src in self.sources:
                val = feed[src.name] if isinstance(feed, dict) else feed
                while True:  # bounded put that can't deadlock the driver
                    try:
                        in_queues[id(src)][0].put(val, timeout=1.0)
                        break
                    except queue.Full:
                        if errors:
                            raise errors[0]
        for src in self.sources:
            in_queues[id(src)][0].put(_STOP)

        for t in threads:
            t.join(timeout=timeout)
            if t.is_alive():
                raise TimeoutError(f"{t.name} did not finish")
        if errors:
            raise errors[0]

        outs = []
        for _ in range(len(feeds)):
            if len(self.sinks) == 1:
                q0 = sink_out[self.sinks[0].name]
                if q0.empty():
                    break
                outs.append(q0.get())
            else:
                outs.append({name: q.get() for name, q in sink_out.items()
                             if not q.empty()})
        return outs
