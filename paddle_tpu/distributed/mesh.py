"""Device mesh + hybrid topology.

TPU-native replacement for the reference 4-axis process topology
(/root/reference/python/paddle/distributed/fleet/base/topology.py:51
CommunicateTopology, :133 HybridCommunicateGroup): instead of building NCCL
communicators per axis, we build ONE jax.sharding.Mesh whose named axes
(dp/pp/sharding/mp/sp/ep subsets) drive GSPMD partitioning; per-axis "groups"
are views over mesh axes.
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_GLOBAL_MESH: Optional[Mesh] = None
_GLOBAL_HCG: Optional["HybridCommunicateGroup"] = None


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """jax.shard_map across JAX versions (top-level since 0.4.31+ with
    check_vma; jax.experimental.shard_map with check_rep before)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def init_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create and install the global mesh, e.g. init_mesh({"dp": 2, "mp": 4}).

    Axis sizes must multiply to the device count (axes of size 1 allowed).
    """
    global _GLOBAL_MESH
    devices = devices if devices is not None else jax.devices()
    names = [k for k, v in axes.items()]
    sizes = [int(v) for v in axes.values()]
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(
            f"mesh {axes} needs {total} devices, have {len(devices)}")
    arr = np.asarray(devices).reshape(sizes)
    _GLOBAL_MESH = Mesh(arr, tuple(names))
    return _GLOBAL_MESH


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH


def reset_mesh():
    """Clear the process-global mesh + HCG (the teardown half of
    fleet.init; reference analog: fleet_base stop_worker releasing the
    communication groups).  Callers should prefer fleet.shutdown()."""
    global _GLOBAL_MESH, _GLOBAL_HCG
    _GLOBAL_MESH = None
    _GLOBAL_HCG = None


def set_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    return mesh


@contextlib.contextmanager
def use_mesh(mesh):
    """Temporarily install ``mesh`` as the global mesh (restored on
    exit).  Lets a step trace against a specific — possibly abstract —
    mesh without clobbering the process-global one."""
    global _GLOBAL_MESH
    prev = _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    try:
        yield mesh
    finally:
        _GLOBAL_MESH = prev


def abstract_mesh(axes: Dict[str, int]):
    """A devices-free ``jax.sharding.AbstractMesh`` over named axes, e.g.
    ``abstract_mesh({"data": 2, "sp": 2})``.  Good enough for tracing
    (shard_map, with_sharding_constraint) under ``make_jaxpr`` — which is
    all the static analyzers need — without claiming real chips."""
    from jax.sharding import AbstractMesh

    pairs = tuple((str(k), int(v)) for k, v in axes.items())
    try:
        return AbstractMesh(pairs)
    except TypeError:
        # newer signature: AbstractMesh(shape_tuple, axis_names)
        return AbstractMesh(tuple(s for _, s in pairs),
                            tuple(n for n, _ in pairs))


def fleet_mesh(dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
               sp_degree=1, ep_degree=1, devices=None) -> Mesh:
    """Fleet-style hybrid mesh with canonical axis order [dp, pp, sharding,
    sp, ep, mp] (the reference's order is [data, pipe, sharding, model],
    topology.py:159)."""
    axes = {}
    for name, deg in (("dp", dp_degree), ("pp", pp_degree),
                      ("sharding", sharding_degree), ("sp", sp_degree),
                      ("ep", ep_degree), ("mp", mp_degree)):
        if deg and deg > 1:
            axes[name] = deg
    if not axes:
        axes = {"dp": 1}
    n = int(np.prod(list(axes.values())))
    devices = devices if devices is not None else jax.devices()
    if n != len(devices):
        # pad with a trailing dp axis if degrees underspecify the devices
        if len(devices) % n == 0 and "dp" not in axes:
            axes = {"dp": len(devices) // n, **axes}
        elif len(devices) % n == 0 and "dp" in axes:
            axes["dp"] *= len(devices) // n
        else:
            raise ValueError(
                f"degrees {axes} incompatible with {len(devices)} devices")
    return init_mesh(axes, devices)


class CommunicateTopology:
    """Rank/coordinate bookkeeping over hybrid axes (reference:
    topology.py:51)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*[range(d) for d in dims]))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return len(self.coordinate)

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate) if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All groups along axis_name: lists of ranks varying only that axis."""
        axis = self._parallel_names.index(axis_name)
        others = [i for i in range(len(self._dims)) if i != axis]
        groups = {}
        for rank, coord in enumerate(self.coordinate):
            key = tuple(coord[i] for i in others)
            groups.setdefault(key, []).append(rank)
        return list(groups.values())


class _AxisGroup:
    """A communication 'group' = one mesh axis (or the trivial group)."""

    def __init__(self, axis_name: Optional[str], nranks: int, rank: int,
                 ranks: Sequence[int]):
        self.axis_name = axis_name
        self.nranks = nranks
        self.rank = rank
        self.ranks = list(ranks)
        self.id = hash((axis_name, tuple(ranks))) & 0x7FFFFFFF

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def process_group(self):
        return self


class HybridCommunicateGroup:
    """Reference topology.py:133 analog over the global Mesh."""

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 mesh: Optional[Mesh] = None):
        self._mesh = mesh or get_mesh()
        self._topo = topology
        global _GLOBAL_HCG
        _GLOBAL_HCG = self

    def _axis_size(self, names):
        if self._mesh is None:
            return 1
        size = 1
        for n in names:
            if n in self._mesh.shape:
                size *= self._mesh.shape[n]
        return size

    # --- degrees
    def get_data_parallel_world_size(self):
        return self._axis_size(["dp"])

    def get_model_parallel_world_size(self):
        return self._axis_size(["mp"])

    def get_pipe_parallel_world_size(self):
        return self._axis_size(["pp"])

    def get_sharding_parallel_world_size(self):
        return self._axis_size(["sharding"])

    def get_sep_parallel_world_size(self):
        return self._axis_size(["sp"])

    def get_expert_parallel_world_size(self):
        return self._axis_size(["ep"])

    # --- ranks (single-controller SPMD: the driving process is rank 0 on
    # every axis; per-device ranks exist only inside compiled programs)
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # --- groups
    def _group(self, axis):
        size = self._axis_size([axis])
        return _AxisGroup(axis if size > 1 else None, size, 0, range(size))

    def get_data_parallel_group(self):
        return self._group("dp")

    def get_model_parallel_group(self):
        return self._group("mp")

    def get_pipe_parallel_group(self):
        return self._group("pp")

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_expert_parallel_group(self):
        return self._group("ep")

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline helpers
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self.get_pipe_parallel_world_size() - 1

    @property
    def nranks(self):
        return self._mesh.size if self._mesh is not None else 1

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self.get_pipe_parallel_world_size() > 1:
            return "pipeline"
        if self.get_sharding_parallel_world_size() > 1:
            return "sharding"
        if self.get_model_parallel_world_size() > 1:
            return "model"
        return "data"


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _GLOBAL_HCG


class ProcessMesh:
    """auto_parallel ProcessMesh analog (reference:
    python/paddle/distributed/auto_parallel/process_mesh.py) — a named view
    over device ids that converts to a jax Mesh."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids).reshape(shape)
        self._shape = list(arr.shape)
        self._ids = arr.flatten().tolist()
        self._dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]

    @property
    def shape(self):
        return self._shape

    @property
    def process_ids(self):
        return self._ids

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def ndim(self):
        return len(self._shape)

    def to_jax_mesh(self) -> Mesh:
        devices = jax.devices()
        arr = np.asarray([devices[i] for i in self._ids]).reshape(self._shape)
        return Mesh(arr, tuple(self._dim_names))

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self._shape == other._shape
                and self._ids == other._ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"
