"""paddle.distributed.launch: multi-host launch controller.

Reference: python/paddle/distributed/launch/ (Controller builds a Pod of
Containers, watches exits, restarts per --elastic_level; rendezvous via
HTTP/etcd Master, controllers/master.py:66).

TPU-native: ONE process per host drives all local chips (SPMD), so the
controller's job is per-host process supervision + TCPStore rendezvous
(jax.distributed handles the device-runtime handshake once env is set).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from ..store import TCPStore


class Container:
    """One supervised training process (reference: job/container.py)."""

    def __init__(self, cmd: List[str], env: dict, log_path: Optional[str] = None):
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0

    def start(self):
        log = open(self.log_path, "ab") if self.log_path else None
        self.proc = subprocess.Popen(
            self.cmd, env={**os.environ, **self.env},
            stdout=log or None, stderr=subprocess.STDOUT if log else None)
        return self.proc

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class Controller:
    """Per-host supervisor with elastic restart (reference:
    controllers/collective.py:23 CollectiveController)."""

    def __init__(self, script: str, script_args: List[str], nnodes: int = 1,
                 rank: int = 0, master: str = "127.0.0.1:6170",
                 elastic_level: int = 0, max_restarts: int = 3,
                 log_dir: str = "log"):
        self.script = script
        self.script_args = script_args
        self.nnodes = nnodes
        self.rank = rank
        self.master_addr, self.master_port = master.split(":")
        self.elastic_level = elastic_level
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.store: Optional[TCPStore] = None

    def _rendezvous(self):
        """All nodes register endpoints; everyone learns the full list."""
        is_master = self.rank == 0
        self.store = TCPStore(self.master_addr, int(self.master_port),
                              is_master=is_master, world_size=self.nnodes,
                              timeout=300.0)
        self.store.set(f"node/{self.rank}", f"{self.master_addr}")
        self.store.barrier("rendezvous", timeout=300.0)
        endpoints = ",".join(
            f"{self.master_addr}:{int(self.master_port) + 1}"
            for _ in range(self.nnodes))
        return endpoints

    def _build_env(self, endpoints):
        return {
            "PADDLE_TRAINER_ID": str(self.rank),
            "PADDLE_TRAINERS_NUM": str(self.nnodes),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": endpoints.split(",")[self.rank]
            if self.nnodes > 1 else endpoints,
            "PADDLE_RANK_IN_NODE": "0",
        }

    def run(self):
        os.makedirs(self.log_dir, exist_ok=True)
        endpoints = self._rendezvous() if self.nnodes > 1 else "127.0.0.1:6170"
        env = self._build_env(endpoints)
        container = Container(
            [sys.executable, self.script] + self.script_args, env,
            os.path.join(self.log_dir, f"worker.{self.rank}.log"))
        container.start()
        while True:
            code = container.poll()
            if code is None:
                time.sleep(1)
                # heartbeat so peers can detect dead nodes
                if self.store is not None:
                    self.store.set(f"heartbeat/{self.rank}",
                                   str(time.time()))
                continue
            if code == 0:
                return 0
            if self.elastic_level > 0 and \
                    container.restarts < self.max_restarts:
                container.restarts += 1
                time.sleep(3)
                container.start()
                continue
            return code


def launch(script=None, args=None, nnodes=1, rank=None, master=None,
           elastic_level=0, max_restarts=3, log_dir="log", **kwargs):
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", 0))
    master = master or os.environ.get("PADDLE_MASTER", "127.0.0.1:6170")
    ctrl = Controller(script, args or [], nnodes, rank, master, elastic_level,
                      max_restarts, log_dir)
    return ctrl.run()
