"""python -m paddle_tpu.distributed.launch --nnodes N --rank R script.py args"""
import argparse
import sys

from . import launch


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--master", type=str, default=None)
    parser.add_argument("--elastic_level", type=int, default=0)
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--log_dir", type=str, default="log")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    sys.exit(launch(args.script, args.script_args, args.nnodes, args.rank,
                    args.master, args.elastic_level, args.max_restarts,
                    args.log_dir))


if __name__ == "__main__":
    main()
