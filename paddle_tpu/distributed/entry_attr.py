"""Sparse-embedding entry policies (reference:
python/paddle/distributed/entry_attr.py) — admission/eviction config for
``paddle.static.nn.sparse_embedding`` rows on a parameter server.  Pure
config descriptors: ``_to_attr()`` is the wire format the PS table reads."""
from __future__ import annotations

__all__ = ["ProbabilityEntry", "CountFilterEntry", "ShowClickEntry"]


class EntryAttr:
    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError("EntryAttr is abstract")


class ProbabilityEntry(EntryAttr):
    """Admit a new feature id with fixed probability."""

    def __init__(self, probability):
        super().__init__()
        if not isinstance(probability, float) or not 0 < probability < 1:
            raise ValueError("probability must be a float in (0,1)")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self):
        return ":".join([self._name, str(self._probability)])


class CountFilterEntry(EntryAttr):
    """Admit a feature id once it has been seen `count_filter` times."""

    def __init__(self, count_filter):
        super().__init__()
        if not isinstance(count_filter, int) or count_filter < 0:
            raise ValueError(
                "count_filter must be a valid integer greater or equal "
                "than 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self):
        return ":".join([self._name, str(self._count_filter)])


class ShowClickEntry(EntryAttr):
    """Score rows by the named show/click slots (CTR-style eviction)."""

    def __init__(self, show_name, click_name):
        super().__init__()
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show_name click_name must be a str")
        self._name = "show_click_entry"
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self):
        return ":".join([self._name, self._show_name, self._click_name])
