"""Cross-mesh checkpoint conversion (reference:
python/paddle/distributed/auto_parallel/converter.py — merge per-rank
shards saved under one ProcessMesh/dims_mapping and re-slice them for a
different one).

The orbax path (checkpoint.py) reshards natively; this Converter covers the
reference's explicit API: numpy-level merge + re-split driven by strategy
dicts {name: {"process_shape": [...], "dims_mapping": [...]}} where
dims_mapping[i] = mesh axis tensor-dim i is sharded on (-1 = replicated).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

__all__ = ["Converter"]


def _rank_coords(process_shape):
    """Rank -> mesh coordinates, row-major over the process grid."""
    coords = []
    n = int(np.prod(process_shape))
    for r in range(n):
        c, rem = [], r
        for dim in reversed(process_shape):
            c.append(rem % dim)
            rem //= dim
        coords.append(tuple(reversed(c)))
    return coords


def merge_shards(shards: List[np.ndarray], process_shape,
                 dims_mapping) -> np.ndarray:
    """Reassemble the global tensor from per-rank shards."""
    coords = _rank_coords(process_shape)
    sample = shards[0]
    global_shape = list(sample.shape)
    for tdim, mdim in enumerate(dims_mapping):
        if mdim >= 0:
            global_shape[tdim] = sample.shape[tdim] * process_shape[mdim]
    out = np.zeros(global_shape, sample.dtype)
    for rank, shard in enumerate(shards):
        idx = []
        for tdim, mdim in enumerate(dims_mapping):
            if mdim >= 0:
                i = coords[rank][mdim]
                step = shard.shape[tdim]
                idx.append(slice(i * step, (i + 1) * step))
            else:
                idx.append(slice(None))
        out[tuple(idx)] = shard
    return out


def split_tensor(tensor: np.ndarray, process_shape,
                 dims_mapping) -> List[np.ndarray]:
    """Slice the global tensor into one shard per rank."""
    coords = _rank_coords(process_shape)
    shards = []
    for rank in range(int(np.prod(process_shape))):
        idx = []
        for tdim, mdim in enumerate(dims_mapping):
            if mdim >= 0:
                parts = process_shape[mdim]
                step = tensor.shape[tdim] // parts
                i = coords[rank][mdim]
                idx.append(slice(i * step, (i + 1) * step))
            else:
                idx.append(slice(None))
        shards.append(np.ascontiguousarray(tensor[tuple(idx)]))
    return shards


class Converter:
    """convert(): pre-strategy per-rank shards -> cur-strategy shards."""

    def __init__(self, tensors_dict: Dict[str, List[np.ndarray]],
                 pre_strategy: Dict[str, dict],
                 cur_strategy: Dict[str, dict]):
        self.tensors_dict = tensors_dict
        self.pre_strategy = pre_strategy
        self.cur_strategy = cur_strategy

    def convert(self) -> Dict[str, List[np.ndarray]]:
        out = {}
        for name, shards in self.tensors_dict.items():
            if not isinstance(shards, (list, tuple)):
                shards = [shards]
            shards = [np.asarray(s) for s in shards]
            pre = self.pre_strategy.get(name)
            cur = self.cur_strategy.get(name)
            merged = (merge_shards(shards, pre["process_shape"],
                                   pre["dims_mapping"])
                      if pre is not None else shards[0])
            if cur is None:
                out[name] = [merged]
            else:
                out[name] = split_tensor(merged, cur["process_shape"],
                                         cur["dims_mapping"])
        return out
