"""Segmentation helpers for pipeline stages (reference: pp_layers.py
SegmentLayers — uniform and by-layer strategies)."""
from __future__ import annotations

from typing import List, Tuple


def segment_uniform(num_items: int, num_parts: int) -> List[Tuple[int, int]]:
    base = num_items // num_parts
    extra = num_items % num_parts
    bounds = []
    start = 0
    for i in range(num_parts):
        size = base + (1 if i < extra else 0)
        bounds.append((start, start + size))
        start += size
    return bounds
