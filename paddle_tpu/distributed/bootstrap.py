"""Multi-process cluster bootstrap (reference: paddle.distributed.launch +
python/paddle/distributed/parallel.py:91 init flow).

``initialize_cluster`` wraps ``jax.distributed.initialize()`` with

* env-var autodiscovery (``PADDLE_TPU_COORDINATOR`` / ``_NUM_PROCESSES`` /
  ``_PROCESS_ID``, falling back to the reference's ``PADDLE_TRAINER_*``
  triple), so launchers only have to export a handful of variables;
* idempotent re-entry guards — a second call with compatible arguments is
  a no-op returning the live :class:`ClusterInfo`; a conflicting call
  raises instead of silently re-initializing a different topology;
* the CPU-emulation details that make a *real* multi-controller runtime
  run in CI with no TPU: gloo TCP collectives must be selected before the
  CPU backend is created (the env var alone does not bind on this jaxlib;
  ``jax.config.update("jax_cpu_collectives_implementation", "gloo")`` is
  required), and ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
  gives each process N emulated local devices.

``spawn_local(n, target)`` forks N ``JAX_PLATFORMS=cpu`` subprocesses
pre-wired to rendezvous on a free localhost port — the harness tier-1 CI
and ``examples/elastic_train.py`` use to exercise process-death chaos.

``ProcessContext`` is the small seam the sharded checkpointer and the
S209 cross-process aggregation are written against: ``index``/``count``
plus a named ``barrier``.  ``cluster_context()`` returns the live one;
``emulated_process_context(index, count)`` overrides it in-process so
protocol tests can play both sides of a 2-process save sequentially
without paying for subprocesses.
"""
from __future__ import annotations

import dataclasses
import os
import socket
import subprocess
import sys
import time
import warnings
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "ClusterInfo",
    "ProcessContext",
    "barrier",
    "cluster_context",
    "emulated_process_context",
    "initialize_cluster",
    "is_coordinator",
    "process_count",
    "process_index",
    "shutdown_cluster",
    "spawn_local",
]

# -- env autodiscovery ------------------------------------------------------

_ENV_COORD = ("PADDLE_TPU_COORDINATOR", "JAX_COORDINATOR_ADDRESS")
_ENV_NPROC = ("PADDLE_TPU_NUM_PROCESSES", "JAX_NUM_PROCESSES",
              "PADDLE_TRAINERS_NUM")
_ENV_PID = ("PADDLE_TPU_PROCESS_ID", "JAX_PROCESS_ID", "PADDLE_TRAINER_ID")

_DEFAULT_BARRIER_TIMEOUT_S = 120.0


def _env_first(names: Sequence[str]) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v not in (None, ""):
            return v
    return None


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    """What ``initialize_cluster`` resolved and activated."""

    coordinator: Optional[str]
    num_processes: int
    process_id: int
    local_device_count: int
    cpu_collectives: Optional[str] = None

    @property
    def multiprocess(self) -> bool:
        return self.num_processes > 1


_CLUSTER: Optional[ClusterInfo] = None


def _jax():
    import jax

    return jax


def initialize_cluster(coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       *,
                       cpu_collectives: str = "gloo",
                       initialization_timeout: int = 60) -> ClusterInfo:
    """Join (or declare) the multi-controller runtime.

    Arguments default from the environment (``PADDLE_TPU_COORDINATOR``,
    ``PADDLE_TPU_NUM_PROCESSES``, ``PADDLE_TPU_PROCESS_ID``, then the
    ``JAX_*`` / ``PADDLE_TRAINER_*`` equivalents).  With no coordinator
    and no multi-process env, this records a single-process cluster and
    never touches ``jax.distributed`` — safe to call unconditionally at
    program start.

    Re-entry: a second call that agrees with the live cluster returns the
    existing :class:`ClusterInfo`; a disagreeing call raises
    ``RuntimeError`` (a process cannot belong to two clusters).
    """
    global _CLUSTER

    coordinator = coordinator or _env_first(_ENV_COORD)
    if num_processes is None:
        v = _env_first(_ENV_NPROC)
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = _env_first(_ENV_PID)
        process_id = int(v) if v is not None else None

    if num_processes is None:
        num_processes = 1 if coordinator is None else None
    if num_processes == 1 and process_id is None:
        process_id = 0

    if _CLUSTER is not None:
        same = ((coordinator is None or coordinator == _CLUSTER.coordinator)
                and (num_processes is None
                     or num_processes == _CLUSTER.num_processes)
                and (process_id is None or process_id == _CLUSTER.process_id))
        if not same:
            raise RuntimeError(
                f"initialize_cluster re-entered with conflicting topology: "
                f"live={_CLUSTER} requested=(coordinator={coordinator!r}, "
                f"num_processes={num_processes}, process_id={process_id})")
        return _CLUSTER

    jax = _jax()
    if num_processes == 1:
        _CLUSTER = ClusterInfo(coordinator=None, num_processes=1,
                               process_id=0,
                               local_device_count=len(jax.local_devices()))
        _export_cluster_gauges(_CLUSTER)
        return _CLUSTER

    if coordinator is None or num_processes is None or process_id is None:
        raise ValueError(
            "multi-process initialize_cluster needs coordinator, "
            "num_processes and process_id (set PADDLE_TPU_COORDINATOR / "
            "PADDLE_TPU_NUM_PROCESSES / PADDLE_TPU_PROCESS_ID or pass them "
            f"explicitly); got coordinator={coordinator!r}, "
            f"num_processes={num_processes}, process_id={process_id}")

    applied_collectives = None
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if cpu_collectives and ("cpu" in platforms or platforms == ""):
        # must land before the CPU client exists; if the backend is
        # already up this is a silent no-op and collectives will fail
        # with "Multiprocess computations aren't implemented on the CPU
        # backend" — surface that early.
        if _backends_initialized():
            warnings.warn(
                "initialize_cluster: the XLA backend is already "
                "initialized; CPU collectives implementation "
                f"'{cpu_collectives}' cannot be applied. Call "
                "initialize_cluster before any jax.devices()/computation.",
                RuntimeWarning, stacklevel=2)
        else:
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  cpu_collectives)
                applied_collectives = cpu_collectives
            except Exception:  # older jaxlib without the flag
                applied_collectives = None

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id,
                               initialization_timeout=initialization_timeout)
    _CLUSTER = ClusterInfo(coordinator=coordinator,
                           num_processes=num_processes,
                           process_id=process_id,
                           local_device_count=len(jax.local_devices()),
                           cpu_collectives=applied_collectives)
    _export_cluster_gauges(_CLUSTER)
    return _CLUSTER


def _backends_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def _export_cluster_gauges(info: ClusterInfo) -> None:
    try:
        from ..observability import registry as _obsreg

        reg = _obsreg.get_registry()
        reg.gauge("cluster_process_count",
                  "processes in the multi-controller runtime",
                  ).set(info.num_processes)
        reg.gauge("cluster_process_index",
                  "this process's index in the cluster").set(info.process_id)
        reg.gauge("cluster_local_devices",
                  "devices addressable by this process",
                  ).set(info.local_device_count)
    except Exception:
        pass


def shutdown_cluster() -> None:
    """Tear down ``jax.distributed`` (if up) and forget the cluster."""
    global _CLUSTER
    if _CLUSTER is not None and _CLUSTER.multiprocess:
        try:
            _jax().distributed.shutdown()
        except Exception:
            pass
    _CLUSTER = None


# -- process context --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ProcessContext:
    """index/count plus a named barrier — the seam sharded checkpointing
    and cross-process reconciliation are written against."""

    index: int
    count: int
    barrier_fn: Optional[Callable[[str], None]] = None

    def barrier(self, name: str,
                timeout_s: float = _DEFAULT_BARRIER_TIMEOUT_S) -> None:
        if self.count <= 1:
            return
        if self.barrier_fn is not None:
            self.barrier_fn(name)
            return
        _distributed_barrier(name, timeout_s)

    @property
    def is_coordinator(self) -> bool:
        return self.index == 0


_EMULATED: List[ProcessContext] = []


class emulated_process_context:
    """Pretend to be process ``index`` of ``count`` inside one process.

    Barriers no-op (protocol tests drive the per-process save calls
    sequentially, non-coordinators first, coordinator last — the same
    ordering the real barrier enforces).  Nests; the innermost wins.
    """

    def __init__(self, index: int, count: int,
                 barrier: Optional[Callable[[str], None]] = None):
        if not 0 <= index < count:
            raise ValueError(f"index {index} out of range for count {count}")
        self._ctx = ProcessContext(index=index, count=count,
                                   barrier_fn=barrier or (lambda name: None))

    def __enter__(self) -> ProcessContext:
        _EMULATED.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        _EMULATED.pop()


def cluster_context() -> ProcessContext:
    """The live process context: emulation override if active, else the
    real runtime (jax.process_index/count)."""
    if _EMULATED:
        return _EMULATED[-1]
    jax = _jax()
    try:
        idx, cnt = jax.process_index(), jax.process_count()
    except Exception:
        idx, cnt = 0, 1
    return ProcessContext(index=idx, count=cnt)


def process_index() -> int:
    return cluster_context().index


def process_count() -> int:
    return cluster_context().count


def is_coordinator() -> bool:
    return cluster_context().index == 0


def barrier(name: str,
            timeout_s: float = _DEFAULT_BARRIER_TIMEOUT_S) -> None:
    """Block until every process reaches the same named barrier.

    Uses the distributed-runtime coordination service when available
    (which — unlike a psum over devices — carries a timeout, so a dead
    peer turns into an exception instead of a hang), falling back to
    ``sync_global_devices``.
    """
    cluster_context().barrier(name, timeout_s)


def _distributed_barrier(name: str, timeout_s: float) -> None:
    jax = _jax()
    client = None
    try:
        from jax._src import distributed as _dist

        client = _dist.global_state.client
    except Exception:
        client = None
    if client is not None:
        client.wait_at_barrier(name, timeout_in_ms=int(timeout_s * 1000))
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


# -- local spawn harness ----------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_local(num_processes: int,
                argv: Sequence[str],
                *,
                devices_per_process: int = 1,
                env: Optional[Dict[str, str]] = None,
                timeout_s: float = 600.0,
                grace_s: float = 10.0,
                stream_output: bool = True) -> List[int]:
    """Launch ``num_processes`` copies of ``argv`` as an emulated CPU
    cluster and supervise them; returns the per-process exit codes.

    Each child gets ``JAX_PLATFORMS=cpu``, ``XLA_FLAGS`` forcing
    ``devices_per_process`` host devices, and the ``PADDLE_TPU_*`` triple
    pointing at a fresh localhost coordinator — so a child only has to
    call :func:`initialize_cluster` (no arguments) to join.

    Supervision mirrors a TPU fleet controller: the first child to die
    takes the job with it — remaining children are terminated after
    ``grace_s`` (a dead peer would otherwise hang every collective).
    """
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    port = _free_port()
    base = dict(os.environ)
    base.update(env or {})
    base["JAX_PLATFORMS"] = "cpu"
    base["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_process}")
    base["PADDLE_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    base["PADDLE_TPU_NUM_PROCESSES"] = str(num_processes)
    base.pop("PALLAS_AXON_POOL_IPS", None)

    procs: List[subprocess.Popen] = []
    for i in range(num_processes):
        child_env = dict(base)
        child_env["PADDLE_TPU_PROCESS_ID"] = str(i)
        procs.append(subprocess.Popen(
            list(argv), env=child_env,
            stdout=None if stream_output else subprocess.DEVNULL,
            stderr=None if stream_output else subprocess.DEVNULL))

    deadline = time.monotonic() + timeout_s
    rcs: List[Optional[int]] = [None] * num_processes
    try:
        while any(rc is None for rc in rcs):
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    rcs[i] = p.poll()
            exited = [rc for rc in rcs if rc is not None]
            if any(rc != 0 for rc in exited):
                # first failure kills the job (fleet-controller semantics)
                _terminate_rest(procs, rcs, grace_s)
                break
            if time.monotonic() > deadline:
                _terminate_rest(procs, rcs, grace_s=0.0)
                raise TimeoutError(
                    f"spawn_local: cluster did not finish in {timeout_s}s "
                    f"(exit codes so far: {rcs})")
            time.sleep(0.05)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return [p.wait() for p in procs]


def _terminate_rest(procs: List[subprocess.Popen],
                    rcs: List[Optional[int]], grace_s: float) -> None:
    live = [p for p in procs if p.poll() is None]
    if not live:
        return
    end = time.monotonic() + grace_s
    while time.monotonic() < end and any(p.poll() is None for p in live):
        time.sleep(0.05)
    for p in live:
        if p.poll() is None:
            p.terminate()
    for p in live:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m paddle_tpu.distributed.bootstrap -n 2 script.py
    [args...]`` (tools/mp_launch.py is the thin wrapper)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="mp_launch",
        description="launch an emulated multi-process CPU jax cluster")
    parser.add_argument("-n", "--num-processes", type=int, default=2)
    parser.add_argument("-d", "--devices-per-process", type=int, default=1)
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    rcs = spawn_local(
        args.num_processes,
        [sys.executable, args.script, *args.script_args],
        devices_per_process=args.devices_per_process,
        timeout_s=args.timeout)
    print(f"mp_launch: exit codes {rcs}")
    return 0 if all(rc == 0 for rc in rcs) else 1


if __name__ == "__main__":
    sys.exit(main())
