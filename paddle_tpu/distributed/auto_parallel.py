"""Semi-automatic parallelization (reference:
python/paddle/distributed/auto_parallel/: Engine engine.py:54, ProcessMesh,
completion.py shard propagation, partitioner.py, reshard.py, planner).

The reference's pipeline — annotate a few tensors, propagate dist_attrs,
partition the program, insert reshards — is exactly GSPMD's job: here
shard_tensor/mark_sharding are the annotations, XLA's sharding propagation
is `completion`, SPMD partitioner is `partitioner`, and device_put is
`reshard`.  Engine wraps that flow with the reference's fit/evaluate API.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from .converter import Converter  # noqa: F401
from .mesh import ProcessMesh, get_mesh, set_mesh
from .sharding import shard_tensor as _shard_tensor


def shard_tensor(x, process_mesh=None, shard_spec=None, placements=None):
    """auto_parallel.shard_tensor: spec names map to mesh axes."""
    spec = placements if placements is not None else shard_spec
    return _shard_tensor(x, mesh=process_mesh, placements=spec)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None):
    from .sharding import shard_op as _shard_op

    return _shard_op(op_fn, process_mesh, in_shard_specs, out_shard_specs)


class Strategy:
    """auto_parallel Strategy (subset)."""

    def __init__(self):
        self.auto_mode = "semi"
        self.amp = _Toggle()
        self.recompute = _Toggle()
        self.sharding = _Toggle()
        self.gradient_merge = _Toggle()


class _Toggle:
    def __init__(self):
        self.enable = False

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class Engine:
    """reference engine.py:54: prepare/fit/evaluate/predict with automatic
    distribution over the current mesh."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy or Strategy()
        self._step_fn = None
        self._plan = None

    def _build(self):
        from .. import jit

        # full-auto planning fires on ANY build path (fit/evaluate call
        # _build on demand without prepare(), like the reference engine)
        if self.strategy.auto_mode == "full" and self.model is not None \
                and self._plan is None:
            mesh = get_mesh()
            if mesh is not None:
                from .planner import Planner

                self._plan = Planner(mesh).apply(self.model)

        model, loss_fn, optimizer = self.model, self.loss, self.optimizer

        def train_step(x, y):
            out = model(x)
            l = loss_fn(out, y)
            l.backward()
            optimizer.step()
            optimizer.clear_grad()
            return l

        self._step_fn = jit.to_static(train_step)

        def eval_step(x, y):
            out = model(x)
            return loss_fn(out, y)

        self._eval_fn = jit.to_static(eval_step)

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        self._build()  # planning happens inside _build (any entry path)

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            valid_data=None, collate_fn=None, verbose=1):
        from ..io import DataLoader, Dataset

        if self._step_fn is None:
            self._build()
        loader = DataLoader(train_data, batch_size=batch_size, shuffle=True) \
            if isinstance(train_data, Dataset) else train_data
        history = []
        mesh = get_mesh()
        for epoch in range(epochs):
            losses = []
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                x, y = batch[0], batch[1]
                if mesh is not None and "dp" in mesh.shape:
                    x = _shard_tensor(x, placements=["dp"])
                    y = _shard_tensor(y, placements=["dp"])
                losses.append(float(np.asarray(
                    self._step_fn(x, y).numpy())))
            history.append(float(np.mean(losses)) if losses else None)
            if verbose:
                print(f"epoch {epoch}: loss={history[-1]}")
        return {"loss": history}

    def evaluate(self, eval_data, batch_size=1, steps=None, collate_fn=None,
                 verbose=1):
        from ..io import DataLoader, Dataset

        if self._step_fn is None:
            self._build()
        loader = DataLoader(eval_data, batch_size=batch_size) \
            if isinstance(eval_data, Dataset) else eval_data
        losses = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            losses.append(float(np.asarray(
                self._eval_fn(batch[0], batch[1]).numpy())))
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data, batch_size=1, steps=None, collate_fn=None):
        from ..core.dispatch import no_grad_ctx
        from ..io import DataLoader, Dataset

        loader = DataLoader(test_data, batch_size=batch_size) \
            if isinstance(test_data, Dataset) else test_data
        outs = []
        with no_grad_ctx():
            for i, batch in enumerate(loader):
                if steps is not None and i >= steps:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self.model(x).numpy())
        return outs

    def save(self, path, training=True):
        from ..framework.io import save as fsave

        fsave(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None:
            fsave(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        import os

        from ..framework.io import load as fload

        self.model.set_state_dict(fload(path + ".pdparams"))
        if load_optimizer and os.path.exists(path + ".pdopt") and \
                self.optimizer is not None:
            self.optimizer.set_state_dict(fload(path + ".pdopt"))

    def cost(self, mode="train"):
        """Planner cost stub: XLA's own cost model drives scheduling; expose
        compiled HLO stats instead in a later round."""
        return None
