"""Heterogeneous / cross-cluster collectives (reference:
paddle/fluid/distributed/collective/ProcessGroupHeter.h:64 — NCCL inside a
cluster + Gloo between clusters, used for GPU<->NPU/CPU mixed jobs).

TPU-native design: the intra-cluster layer is whatever the normal
collective path provides (XLA collectives over ICI inside a slice, or the
eager cross-process mesh); the INTER-cluster layer rides the host network
(DCN) through the TCPStore rendezvous, exactly where the reference places
Gloo.  Each cluster elects rank 0 as its gateway: gateways all-reduce the
cluster-partial via the store, then re-broadcast locally — the reference's
hierarchical scheme (ProcessGroupHeter::AllReduce) with the store playing
Gloo's role.

The store protocol is round-versioned so repeated collectives reuse keys
without clearing the store.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .collective import ReduceOp, all_reduce, broadcast


class ProcessGroupHeter:
    """Hierarchical collective group spanning clusters.

    Args:
        store: TCPStore shared by ALL clusters (rendezvous over DCN).
        cluster_id: index of this process's cluster.
        n_clusters: number of clusters in the job.
        local_group: optional intra-cluster group (``new_group(...)``)
            passed to the inner all_reduce/broadcast.
        local_rank: this process's rank inside its cluster (rank 0 is the
            cluster gateway that talks to the store).
        gid: group id for bookkeeping.
    """

    def __init__(self, store, cluster_id: int, n_clusters: int,
                 local_group=None, local_rank: int = 0,
                 local_world_size: int = 1, gid: int = 0,
                 timeout: float = 120.0):
        self.store = store
        self.cluster_id = int(cluster_id)
        self.n_clusters = int(n_clusters)
        self.local_group = local_group
        self.local_rank = int(local_rank)
        self.local_world_size = max(1, int(local_world_size))
        self.id = gid
        self.timeout = float(timeout)
        self._round = 0

    # -- helpers --
    def _key(self, op_name: str, cluster: int) -> str:
        return f"heter/{self.id}/{self._round}/{op_name}/{cluster}"

    def _poll_get(self, key: str) -> bytes:
        """Short non-blocking gets in a sleep loop instead of one long
        blocking wait: the TCP client serializes calls under one mutex,
        so a blocking wait would LOCK OUT a same-process peer's set()
        for the whole wait (threaded gateways sharing a store deadlock
        until timeout)."""
        import time

        deadline = time.monotonic() + self.timeout
        while True:
            try:
                return self.store.get(key, wait=False)
            except KeyError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"heter exchange timed out waiting for {key!r}")
                time.sleep(0.005)

    def _exchange(self, op_name: str, payload: np.ndarray) -> list:
        """Gateway (local rank 0) publishes this cluster's array; every
        rank may fetch all peers' arrays.

        The store is a CONTROL path, not a gradient transport (the
        reference rides Gloo for the inter-cluster hop,
        ProcessGroupHeter.h:64): payloads are capped by
        FLAGS_heter_max_payload_mb with a clear error, and moved in
        FLAGS_heter_chunk_mb pieces so one giant value never sits in a
        single store message.  The chunk-count meta key is written LAST —
        the TCP client serializes ops, so a reader that sees the meta key
        is guaranteed every chunk is already published."""
        if self.local_rank == 0:
            self._publish(self._key(op_name, self.cluster_id),
                          payload.tobytes())
        outs = []
        for c in range(self.n_clusters):
            raw = self._fetch(self._key(op_name, c))
            outs.append(np.frombuffer(raw, dtype=payload.dtype)
                        .reshape(payload.shape))
        return outs

    def _publish(self, key: str, data: bytes):
        from ..core.flags import flag

        cap = int(flag("heter_max_payload_mb")) << 20
        if cap and len(data) > cap:
            raise ValueError(
                f"heter gateway payload is {len(data) >> 20} MiB, above "
                f"the {cap >> 20} MiB FLAGS_heter_max_payload_mb cap. "
                "Keep large tensors on the intra-cluster XLA collectives "
                "(fleet hybrid dp/sharding) and reserve the cross-cluster "
                "store hop for small partials; raise the flag via "
                "paddle_tpu.set_flags({'FLAGS_heter_max_payload_mb': N}) "
                "only if you accept the store bandwidth")
        chunk = max(1, int(flag("heter_chunk_mb"))) << 20
        n_chunks = max(1, -(-len(data) // chunk))
        for i in range(n_chunks):
            self.store.set(f"{key}/{i}", data[i * chunk:(i + 1) * chunk])
        self.store.set(key, str(n_chunks).encode())

    def _fetch(self, key: str) -> bytes:
        n_chunks = int(self._poll_get(key))
        return b"".join(self.store.get(f"{key}/{i}", wait=False)
                        for i in range(n_chunks))

    # -- collectives --
    def all_reduce(self, tensor: Tensor, op=ReduceOp.SUM):
        """Intra-cluster all_reduce, inter-cluster combine, local rebcast."""
        # AVG must weight clusters by rank count: reduce local SUMs and
        # divide by the global rank total at the end
        local_op = ReduceOp.SUM if op == ReduceOp.AVG else op
        all_reduce(tensor, op=local_op, group=self.local_group)
        self._round += 1
        if self.n_clusters <= 1:
            if op == ReduceOp.AVG:
                tensor.set_value(np.asarray(tensor.numpy())
                                 / self.local_world_size)
            return tensor
        if self.local_rank == 0:
            partial = np.asarray(tensor.numpy())
            parts = self._exchange("allreduce", partial)
            if op in (ReduceOp.SUM, ReduceOp.AVG):
                total = np.sum(parts, axis=0)
                if op == ReduceOp.AVG:
                    counts = self._exchange(
                        "allreduce_count",
                        np.asarray([self.local_world_size], np.int64))
                    total = total / int(np.sum(counts))
            elif op == ReduceOp.MAX:
                total = np.max(parts, axis=0)
            elif op == ReduceOp.MIN:
                total = np.min(parts, axis=0)
            elif op == ReduceOp.PROD:
                total = np.prod(parts, axis=0)
            else:
                raise ValueError(f"unsupported op {op}")
            tensor.set_value(total.astype(partial.dtype))
        # gateway result reaches the cluster's other ranks
        broadcast(tensor, src=0, group=self.local_group)
        return tensor

    def all_gather(self, tensor: Tensor):
        """Returns a list of per-cluster tensors (gateway view)."""
        self._round += 1
        payload = np.asarray(tensor.numpy())
        parts = self._exchange("allgather", payload)
        return [Tensor(p) for p in parts]

    def broadcast(self, tensor: Tensor, src_cluster: int = 0):
        self._round += 1
        if self.local_rank == 0:
            if self.cluster_id == src_cluster:
                self._publish(self._key("bcast", src_cluster),
                              np.asarray(tensor.numpy()).tobytes())
            raw = self._fetch(self._key("bcast", src_cluster))
            val = np.frombuffer(raw, dtype=np.asarray(
                tensor.numpy()).dtype).reshape(tensor.shape)
            tensor.set_value(val)
        broadcast(tensor, src=0, group=self.local_group)
        return tensor

    def barrier(self):
        """All clusters rendezvous: each GATEWAY increments once; every
        rank polls until all clusters have arrived."""
        self._round += 1
        key = f"heter/{self.id}/{self._round}/barrier"
        if self.local_rank == 0:
            self.store.add(key, 1)
        import time

        # same configurable deadline as _poll_get (ADVICE r2: a group built
        # with timeout=120 must not fail its barriers at a hardcoded 30s)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if self.store.add(key, 0) >= self.n_clusters:
                return
            time.sleep(0.01)
        raise TimeoutError(f"heter barrier timed out after {self.timeout}s")

    def rank(self):
        return self.cluster_id

    def size(self):
        return self.n_clusters
