"""Sharding annotations — the GSPMD front door.

Replaces the reference auto_parallel shard_tensor/dist_attr machinery
(/root/reference/python/paddle/distributed/auto_parallel/) with jax.sharding:
a placement is a PartitionSpec over the global mesh; annotations are
device_put (eager) or with_sharding_constraint (inside a trace).
"""
from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.dispatch import apply, in_static_trace
from ..core.tensor import Tensor
from .mesh import get_mesh


# ---------------------------------------------------------------------------
# canonical SpecLayout — PartitionSpecs per parameter role over the
# data/fsdp/tp axes (SNIPPETS.md [3] idiom).  Authored now, validated
# statically by analysis.shardplan / check_sharding_readiness, and the
# layout the mesh-execution PR will hand to jit in_shardings.
# ---------------------------------------------------------------------------

#: param-role → substrings of the qualified parameter name that select it
_LLAMA_ROLE_PATTERNS = (
    ("embed", ("embed_tokens.weight",)),
    ("lm_head", ("lm_head.weight",)),
    ("attn_qkv", ("q_proj.weight", "k_proj.weight", "v_proj.weight")),
    ("attn_out", ("o_proj.weight",)),
    ("mlp_in", ("gate_proj.weight", "up_proj.weight")),
    ("mlp_out", ("down_proj.weight",)),
    ("norm", ("layernorm.weight", "norm.weight")),
)

#: MoE roles live in their own table: only models that opt into experts
#: carry these parameters, and the default dense ``role_layout()`` must
#: stay clean on an expert-less mesh (S201 checks every listed role).
_MOE_ROLE_PATTERNS = (
    ("moe_router", ("router.weight",)),
    ("moe_expert_in", ("w_gate", "w_up")),
    ("moe_expert_out", ("w_down",)),
)


def llama_param_role(name: str) -> Optional[str]:
    """Map a qualified llama parameter name (``named_parameters`` key) to
    its layout role, or None for a name no pattern covers."""
    for role, pats in _LLAMA_ROLE_PATTERNS + _MOE_ROLE_PATTERNS:
        if any(name.endswith(p) for p in pats):
            return role
    return None


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs per parameter role over named mesh axes.

    Megatron-style tensor parallelism with FSDP weight sharding on the
    orthogonal axis, batch on ``data``:

    - ``attn_qkv`` / ``mlp_in``  ([in, out]): column-parallel — the
      output-feature dim on ``tp``, the input dim sharded by ``fsdp``.
    - ``attn_out`` / ``mlp_out`` ([in, out]): row-parallel — the
      input-feature dim on ``tp`` (the contraction is sharded, so the
      matmul ends in ONE planned all-reduce per block), output on
      ``fsdp``.
    - ``embed`` ([vocab, hidden]): vocab-parallel on ``tp``.
    - ``lm_head`` ([hidden, vocab]): column-parallel (vocab on ``tp``).
    - ``norm``: replicated — RMSNorm weights are a few KiB.

    ``batch_axis`` is where activation batch dims live; the default
    ``data`` is what S208 checks for.  Set it to None (or another axis)
    to express deliberately degenerate layouts — the shardplan CLI's
    injection knob does exactly that.
    """

    data_axis: str = "data"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    batch_axis: Optional[str] = "data"
    #: MoE expert weights ([E, ...]) shard their leading dim here
    expert_axis: str = "expert"
    #: sequence-parallel activations split their sequence dim here
    sp_axis: str = "sp"

    def batch_spec(self) -> PartitionSpec:
        """Spec for activation batch dims (inputs, labels, KV pools)."""
        if self.batch_axis is None:
            return PartitionSpec()
        return PartitionSpec(self.batch_axis)

    def sequence_spec(self) -> PartitionSpec:
        """Spec for [batch, seq, ...] activations on a sequence-parallel
        mesh: batch on ``batch_axis``, sequence on ``sp``."""
        return PartitionSpec(self.batch_axis, self.sp_axis)

    def spec_for_role(self, role: str) -> PartitionSpec:
        table = {
            "embed": PartitionSpec(self.tp_axis, self.fsdp_axis),
            "lm_head": PartitionSpec(self.fsdp_axis, self.tp_axis),
            "attn_qkv": PartitionSpec(self.fsdp_axis, self.tp_axis),
            "attn_out": PartitionSpec(self.tp_axis, self.fsdp_axis),
            "mlp_in": PartitionSpec(self.fsdp_axis, self.tp_axis),
            "mlp_out": PartitionSpec(self.tp_axis, self.fsdp_axis),
            "norm": PartitionSpec(),
            # MoE: router is a few KiB → replicated; stacked expert
            # weights [E, in, out] put experts on the expert axis and
            # keep the Megatron column/row split on the feature dims
            "moe_router": PartitionSpec(),
            "moe_expert_in": PartitionSpec(
                self.expert_axis, self.fsdp_axis, self.tp_axis),
            "moe_expert_out": PartitionSpec(
                self.expert_axis, self.tp_axis, self.fsdp_axis),
        }
        if role not in table:
            raise KeyError(f"unknown param role {role!r}; known roles: "
                           f"{sorted(table)}")
        return table[role]

    def param_spec(self, name: str) -> PartitionSpec:
        """Spec for one qualified parameter name; unmatched names (and
        biases/buffers) replicate — correct, never wrong, just unscaled."""
        role = llama_param_role(name)
        if role is None:
            return PartitionSpec()
        return self.spec_for_role(role)

    def role_layout(self, moe: bool = False) -> Dict[str, PartitionSpec]:
        """``{role: spec}`` — the shape check_sharding_readiness wants.
        ``moe=True`` adds the expert roles (needs an ``expert`` mesh
        axis; the dense default stays clean on a data/fsdp/tp mesh)."""
        roles = _LLAMA_ROLE_PATTERNS + (_MOE_ROLE_PATTERNS if moe else ())
        return {role: self.spec_for_role(role) for role, _ in roles}


def llama_param_specs(model) -> Dict[str, PartitionSpec]:
    """``{param_name: PartitionSpec}`` for every named parameter of a
    llama-family module under the default :class:`SpecLayout`."""
    layout = SpecLayout()
    return {name: layout.param_spec(name)
            for name, _ in model.named_parameters()}


def _pspec(placements) -> PartitionSpec:
    if placements is None:
        return PartitionSpec()
    if isinstance(placements, PartitionSpec):
        return placements
    return PartitionSpec(*placements)


def _context_mesh(mesh, spec: Optional[PartitionSpec] = None
                  ) -> Optional[Mesh]:
    """Resolve the mesh an annotation applies to: the caller's, else the
    global one (distributed.mesh), else the registered MeshExecutor's —
    so annotations inside executor-driven programs are not no-ops.
    When ``spec`` is given and the preferred candidate does not know its
    axes, fall through to one that does (a lingering fleet mesh over
    ``dp/mp`` must not eat an executor-targeted ``fsdp/tp`` spec)."""
    if hasattr(mesh, "to_jax_mesh"):
        return mesh.to_jax_mesh()
    if mesh is not None:
        return mesh
    from .executor import active_mesh

    candidates = [m for m in (get_mesh(), active_mesh()) if m is not None]
    if spec is not None:
        for m in candidates:
            if _spec_axes_known(spec, m):
                return m
    return candidates[0] if candidates else None


def _spec_axes_known(spec: PartitionSpec, mesh: Mesh) -> bool:
    needed = [a for a in jax.tree_util.tree_leaves(tuple(spec)) if a]
    return all(a in mesh.shape for a in needed)


#: (dangling axes, mesh axes) pairs already warned about — the no-op
#: fallback below fires once per distinct mismatch, not per tensor
_warned_dangling: set = set()


def _warn_dangling_axes(spec: PartitionSpec, mesh: Mesh) -> None:
    missing = tuple(sorted({a for a in jax.tree_util.tree_leaves(tuple(spec))
                            if a and a not in mesh.shape}))
    mesh_axes = tuple(mesh.shape)
    key = (missing, mesh_axes)
    if not missing or key in _warned_dangling:
        return
    _warned_dangling.add(key)
    warnings.warn(
        f"sharding spec {spec} names mesh axes {list(missing)} unknown on "
        f"the active mesh (axes {list(mesh_axes)}); the annotation is a "
        "no-op. Build the mesh with those axes (e.g. init_mesh) or drop "
        "them from the spec.",
        RuntimeWarning, stacklevel=3)


def shard_tensor(x: Tensor, mesh: Optional[Mesh] = None, placements=None,
                 dist_attr=None) -> Tensor:
    """Annotate a tensor with a mesh sharding.

    Eager: device_put onto the NamedSharding (actually lays the tensor out
    across chips).  Traced: with_sharding_constraint (GSPMD propagates).
    """
    spec = _pspec(placements)
    mesh = _context_mesh(mesh, spec)
    if mesh is None:
        return x
    if not _spec_axes_known(spec, mesh):
        # a fallback mesh (executor/global) may lack this annotation's
        # axes (e.g. 'sp' on a (data, fsdp, tp) mesh) — keep the old
        # no-op contract rather than erroring mid-model, but say so once
        _warn_dangling_axes(spec, mesh)
        return x
    sharding = NamedSharding(mesh, spec)
    if in_static_trace() or _is_tracer(x._value):
        out = apply("sharding_constraint",
                    lambda v: jax.lax.with_sharding_constraint(v, sharding), x)
        out._sharding_spec = spec
        return out
    out = Tensor(jax.device_put(x._value, sharding),
                 stop_gradient=x.stop_gradient)
    out._grad_node = x._grad_node
    out._output_index = x._output_index
    out._sharding_spec = spec
    return out


def _is_tracer(v):
    return hasattr(v, "aval") and not hasattr(v, "addressable_shards")


def mark_sharding(param: Tensor, placements, mesh=None) -> Tensor:
    """Attach a sharding spec to a Parameter; jit.to_static uses it to build
    in_shardings for the compiled step (and eagerly lays out the weight).

    The mesh context resolves caller-arg → global mesh → registered
    MeshExecutor.  Under tracing the annotation still takes effect as a
    sharding constraint (same contract as fleet's slot pinning) instead
    of silently no-opping."""
    spec = _pspec(placements)
    param._sharding_spec = spec
    mesh = _context_mesh(mesh, spec)
    if mesh is None:
        return param
    if not _spec_axes_known(spec, mesh):
        _warn_dangling_axes(spec, mesh)
        return param
    sharding = NamedSharding(mesh, spec)
    if _is_tracer(param._value):
        param._value = jax.lax.with_sharding_constraint(
            param._value, sharding)
    else:
        param._value = jax.device_put(param._value, sharding)
    return param


def get_sharding_spec(t: Tensor):
    return getattr(t, "_sharding_spec", None)


def shard_op(op_fn, mesh=None, in_placements=None, out_placements=None):
    """Wrap an op so inputs/outputs carry sharding constraints."""

    def wrapped(*args, **kwargs):
        if in_placements is not None:
            args = tuple(
                shard_tensor(a, mesh, p) if isinstance(a, Tensor) and
                p is not None else a
                for a, p in zip(args, in_placements))
        out = op_fn(*args, **kwargs)
        if out_placements is not None and isinstance(out, Tensor):
            out = shard_tensor(out, mesh, out_placements)
        return out

    return wrapped


def reshard(x: Tensor, mesh=None, placements=None) -> Tensor:
    """Change a tensor's layout across the mesh (reference:
    auto_parallel/reshard.py — here it is one device_put; XLA moves bytes)."""
    return shard_tensor(x, mesh, placements)


# ---------------------------------------------------------------------------
# paddle.distributed.sharding module API (reference:
# python/paddle/distributed/sharding/group_sharded.py)
# ---------------------------------------------------------------------------

_GSP_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """ZeRO wrapper (reference group_sharded.py:40: level 'os' shards
    optimizer state, 'os_g' + gradients, 'p_g_os' + parameters).

    GSPMD design: the reference's GroupShardedStage2/3 wrapper classes
    (per-param allgather/reduce-scatter hooks, buffer management) collapse
    into sharding ANNOTATIONS over the mesh's 'sharding' axis — the SPMD
    partitioner inserts the reduce-scatter/allgather pairs and XLA
    schedules them (HLO-verified in tests/test_distributed.py
    TestZeROStages).  buffer_max_size / segment_size / sync_comm are
    therefore accepted-and-ignored: fusion buffers and comm/compute
    overlap are the compiler's job here.  offload=True is rejected rather
    than ignored — parameter offload changes what fits in HBM, so
    silently dropping it would misrepresent capacity."""
    if level not in _GSP_LEVELS:
        raise ValueError(
            f"level must be one of {sorted(_GSP_LEVELS)}, got {level!r}")
    if offload:
        raise NotImplementedError(
            "offload=True (CPU parameter offload) is not supported on the "
            "TPU backend; use paddle.distributed.fleet recompute or a "
            "higher sharding degree instead")
    from .fleet import _pin_slot_shardings, apply_group_sharding
    from .mesh import get_mesh, init_mesh

    mesh = get_mesh()
    if mesh is not None and "sharding" not in mesh.shape:
        # never silently clobber a live mesh — every annotation already
        # made against its axes would dangle
        raise ValueError(
            f"the global mesh {dict(mesh.shape)} has no 'sharding' axis; "
            "build the mesh with one (e.g. fleet.init with "
            "sharding_degree>1, or init_mesh({'dp': ..., 'sharding': ...}))"
            " before calling group_sharded_parallel")
    if mesh is None:
        # group-sharded state spans the WHOLE fleet: a global mesh over
        # every process's devices is the intent, not a per-process one
        n = len(jax.devices())  # lint-tpu: disable=H112
        if group is not None and getattr(group, "nranks", n) != n:
            raise ValueError(
                f"group.nranks={group.nranks} != visible devices {n}: "
                "subgroup sharding needs a hybrid mesh — build it via "
                "fleet.init(strategy with sharding_degree="
                f"{group.nranks}) instead of passing `group` here")
        mesh = init_mesh({"sharding": n})
    apply_group_sharding(model, mesh, stage=_GSP_LEVELS[level])
    # slots inherit the spec at the next step; pin eagerly-existing ones
    if optimizer is not None and hasattr(optimizer, "_accumulators"):
        try:
            _pin_slot_shardings(optimizer)
        except Exception:
            pass  # slots not materialized yet; the step-time hook pins them
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """reference group_sharded.py:181 — gathers shards and saves full
    state.  Orbax/np.save path: state_dict() values are global arrays
    (GSPMD shards are views of the global value), so plain paddle.save
    emits the full model."""
    import os

    from ..framework.io import save as _save

    os.makedirs(output, exist_ok=True)
    _save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        _save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
