"""Sharding annotations — the GSPMD front door.

Replaces the reference auto_parallel shard_tensor/dist_attr machinery
(/root/reference/python/paddle/distributed/auto_parallel/) with jax.sharding:
a placement is a PartitionSpec over the global mesh; annotations are
device_put (eager) or with_sharding_constraint (inside a trace).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.dispatch import apply, in_static_trace
from ..core.tensor import Tensor
from .mesh import get_mesh


def _pspec(placements) -> PartitionSpec:
    if placements is None:
        return PartitionSpec()
    if isinstance(placements, PartitionSpec):
        return placements
    return PartitionSpec(*placements)


def shard_tensor(x: Tensor, mesh: Optional[Mesh] = None, placements=None,
                 dist_attr=None) -> Tensor:
    """Annotate a tensor with a mesh sharding.

    Eager: device_put onto the NamedSharding (actually lays the tensor out
    across chips).  Traced: with_sharding_constraint (GSPMD propagates).
    """
    mesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else \
        (mesh or get_mesh())
    if mesh is None:
        return x
    spec = _pspec(placements)
    sharding = NamedSharding(mesh, spec)
    if in_static_trace() or _is_tracer(x._value):
        out = apply("sharding_constraint",
                    lambda v: jax.lax.with_sharding_constraint(v, sharding), x)
        out._sharding_spec = spec
        return out
    out = Tensor(jax.device_put(x._value, sharding),
                 stop_gradient=x.stop_gradient)
    out._grad_node = x._grad_node
    out._output_index = x._output_index
    out._sharding_spec = spec
    return out


def _is_tracer(v):
    return hasattr(v, "aval") and not hasattr(v, "addressable_shards")


def mark_sharding(param: Tensor, placements) -> Tensor:
    """Attach a sharding spec to a Parameter; jit.to_static uses it to build
    in_shardings for the compiled step (and eagerly lays out the weight)."""
    spec = _pspec(placements)
    param._sharding_spec = spec
    mesh = get_mesh()
    if mesh is not None and not _is_tracer(param._value):
        needed = [a for a in jax.tree_util.tree_leaves(tuple(spec)) if a]
        if all(a in mesh.shape for a in needed):
            param._value = jax.device_put(param._value,
                                          NamedSharding(mesh, spec))
    return param


def get_sharding_spec(t: Tensor):
    return getattr(t, "_sharding_spec", None)


def shard_op(op_fn, mesh=None, in_placements=None, out_placements=None):
    """Wrap an op so inputs/outputs carry sharding constraints."""

    def wrapped(*args, **kwargs):
        if in_placements is not None:
            args = tuple(
                shard_tensor(a, mesh, p) if isinstance(a, Tensor) and
                p is not None else a
                for a, p in zip(args, in_placements))
        out = op_fn(*args, **kwargs)
        if out_placements is not None and isinstance(out, Tensor):
            out = shard_tensor(out, mesh, out_placements)
        return out

    return wrapped


def reshard(x: Tensor, mesh=None, placements=None) -> Tensor:
    """Change a tensor's layout across the mesh (reference:
    auto_parallel/reshard.py — here it is one device_put; XLA moves bytes)."""
    return shard_tensor(x, mesh, placements)
