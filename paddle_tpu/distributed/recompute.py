"""Activation recomputation (reference:
python/paddle/distributed/fleet/utils/recompute.py:331 — PyLayer replay with
RNG-state restore).

TPU-native: jax.checkpoint (remat) IS recompute — XLA rematerializes the
segment inside the compiled program, trading FLOPs for HBM exactly like the
reference's segment replay but without Python-level bookkeeping.  Layer
parameters are threaded through the remat boundary as explicit inputs so
their gradients flow (and so the replay uses the step's own weights).
"""
from __future__ import annotations

from typing import List

import jax

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _collect_params(function) -> List[Tensor]:
    from ..jit import _find_layers

    params = []
    seen = set()
    for layer in _find_layers(function):
        for _, p in layer.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                params.append(p)
    return params


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              **kwargs):
    """Run `function` under rematerialization; grads for both activations
    and the function's Layer parameters flow through the remat boundary."""
    params = _collect_params(function)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    n_args = len(tensor_args)

    def raw_fn(*raw):
        arg_vals, param_vals = raw[:n_args], raw[n_args:]
        saved = [(p._value, p._grad_node, p._output_index) for p in params]
        it = iter(arg_vals)
        new_args = [Tensor(next(it)) if isinstance(a, Tensor) else a
                    for a in args]
        try:
            for p, v in zip(params, param_vals):
                p._value = v
                p._grad_node = None
            # Run with the tape disabled: inside jax.checkpoint the segment
            # must be differentiated by JAX itself (per-op jax.vjp calls
            # would bake non-redifferentiable pallas_call jaxprs into the
            # remat body).  Every op's fn is jax-differentiable by
            # construction, so outer AD flows through.
            from ..core import dispatch as _dispatch

            with _dispatch.no_grad_ctx():
                out = function(*new_args, **kwargs)
        finally:
            for p, (v, node, idx) in zip(params, saved):
                p._value = v
                p._grad_node = node
                p._output_index = idx
        if isinstance(out, Tensor):
            return out._value
        return tuple(o._value if isinstance(o, Tensor) else o for o in out)

    remat_fn = jax.checkpoint(raw_fn)
    return apply("recompute", remat_fn, *(tensor_args + params))


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Checkpoint a Sequential in segments (reference: recompute_sequential;
    first arg is a ctx dict with 'segments')."""
    if not isinstance(ctx, dict):  # called without ctx
        functions, args = ctx, (functions,) + args
        ctx = {}
    segments = ctx.get("segments", 1)
    layers = list(functions)
    n = len(layers)
    per = max(n // max(segments, 1), 1)
    x = args[0]
    i = 0
    while i < n:
        chunk = layers[i:i + per]

        def run_chunk(inp, _chunk=tuple(chunk)):
            for l in _chunk:
                inp = l(inp)
            return inp

        x = recompute(run_chunk, x)
        i += per
    return x


class RecomputeWrapper:
    """Wrap a Layer so its forward runs under remat."""

    def __init__(self, layer):
        self.layer = layer

    def __call__(self, *args, **kwargs):
        return recompute(self.layer, *args, **kwargs)
