"""Comm-efficiency meta-optimizers (reference:
python/paddle/distributed/fleet/meta_optimizers/{localsgd,dgc}_optimizer.py).

The reference implements these as static-graph program rewrites; here they
are optimizer wrappers:

- LocalSGD: run k local steps without gradient sync, then average parameters
  over the data-parallel group.  Under multi-process eager DP each process
  steps on its own gradients; under single-process SPMD the all-reduce is the
  identity (params replicated), so the wrapper degrades to the inner
  optimizer — matching the reference, where localsgd is a no-op at dp=1.
- DGC (Deep Gradient Compression, momentum-corrected top-k sparsification
  with error feedback): the dense complement of each gradient is accumulated
  locally instead of being communicated.  On TPU the payoff of sparsifying an
  ICI all-reduce is small; kept for parity and for DCN-path multi-host DP.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Momentum, Optimizer


class LocalSGDOptimizer:
    """Wraps an inner optimizer; averages params every `k_steps` steps.

    Reference: meta_optimizers/localsgd_optimizer.py (LocalSGDOptimizer,
    AdaptiveLocalSGDOptimizer).  `begin_step` delays the first averaging so
    early noisy steps still sync every step.
    """

    def __init__(self, inner_optimizer: Optimizer, k_steps: int = 1,
                 begin_step: int = 1):
        self._inner = inner_optimizer
        self.k_steps = max(1, int(k_steps))
        self.begin_step = max(1, int(begin_step))
        self._step_count = 0

    def __getattr__(self, name):
        if name.startswith("_inner") or name.startswith("__"):
            raise AttributeError(name)
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        self._step_count += 1
        if self._step_count < self.begin_step:
            sync = True  # pre-warmup: behave like plain DP, sync every step
        else:
            sync = (self._step_count - self.begin_step) % self.k_steps == 0
        if sync:
            self._average_parameters()

    def _average_parameters(self):
        # ReduceOp.AVG keeps this correct in both worlds: inside shard_map it
        # pmeans over the dp axis; in single-controller eager mode all_reduce
        # is the identity (params replicated), so nothing is corrupted.
        from ..collective import ReduceOp, all_reduce

        params = getattr(self._inner, "_parameter_list", None) or []
        for entry in params:
            # _parameter_list may hold parameter-group dicts (same contract
            # as Optimizer._collect_params_grads).
            group = entry.get("params", []) if isinstance(entry, dict) \
                else [entry]
            for p in group:
                all_reduce(p, op=ReduceOp.AVG)

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        return self._inner.minimize(loss, *a, **k)


@functools.partial(jax.jit, donate_argnums=(1,), static_argnames=("k",))
def _dgc_sparsify(g, err, k):
    """Top-k magnitude selection with error feedback.  Returns the sparse
    (masked-dense) gradient to apply/communicate and the new local residual."""
    corrected = g.astype(jnp.float32) + err
    flat = jnp.abs(corrected.ravel())
    if k >= flat.size:
        return corrected, jnp.zeros_like(corrected)
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = jnp.abs(corrected) >= thresh
    sparse = jnp.where(mask, corrected, 0.0)
    residual = corrected - sparse
    return sparse, residual


@functools.partial(jax.jit, donate_argnums=(1, 2), static_argnames=("k",))
def _dgc_momentum_correction(g, u, v, mu, k):
    """DGC with momentum correction (Lin et al. 2018 §3.2; reference
    paddle/fluid/operators/dgc_op.cc): momentum `u` and its running sum `v`
    accumulate *locally* per step; only the top-k of `v` is emitted (and
    zeroed locally).  Sparsifying after correction is what keeps momentum
    stable under aggressive drop rates."""
    gf = g.astype(jnp.float32)
    u = mu * u + gf
    v = v + u
    flat = jnp.abs(v.ravel())
    if k >= flat.size:
        return v, jnp.zeros_like(u), jnp.zeros_like(v)
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = jnp.abs(v) >= thresh
    sparse = jnp.where(mask, v, 0.0)
    # emitted coordinates also clear their momentum (paper's masking trick)
    u = jnp.where(mask, 0.0, u)
    v = jnp.where(mask, 0.0, v)
    return sparse, u, v


class DGCMomentum(Momentum):
    """Momentum with deep-gradient-compression sparsification (reference:
    meta_optimizers/dgc_optimizer.py over paddle/fluid/operators/dgc_op.cc).

    `rampup_begin_step` disables compression for the first steps;
    `sparsity` is the fraction of entries dropped (0.999 in the paper).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 sparsity=0.999, rampup_begin_step=0, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name, **kwargs)
        self.sparsity = float(sparsity)
        self.rampup_begin_step = int(rampup_begin_step)
        self._dgc_step = 0

    def step(self):
        self._dgc_step += 1  # optimizer steps, not per-parameter updates
        super().step()

    def _update_param(self, p, g, lr):
        if self._dgc_step > self.rampup_begin_step and 0.0 < self.sparsity < 1.0:
            u = self._add_accumulator("dgc_u", p, dtype=jnp.float32)
            v = self._add_accumulator("dgc_v", p, dtype=jnp.float32)
            if self._weight_decay:
                g = g.astype(jnp.float32) \
                    + self._weight_decay * p._value.astype(jnp.float32)
            k = max(1, int(g.size * (1.0 - self.sparsity)))
            sparse, u, v = _dgc_momentum_correction(g, u, v, self._momentum,
                                                    k)
            self._set_accumulator("dgc_u", p, u)
            self._set_accumulator("dgc_v", p, v)
            # momentum already folded in; apply as plain (sparse) SGD step
            p._value = (p._value.astype(jnp.float32)
                        - lr * sparse).astype(p._value.dtype)
        else:
            super()._update_param(p, g, lr)


class GradientMergeOptimizer:
    """k-step gradient accumulation before one real update (reference:
    meta_optimizers/gradient_merge_optimizer.py — static-mode conditional
    blocks become value-level jnp.where selects, so the SAME wrapper works
    eagerly and inside a jit-compiled train step).

    Every step: grads accumulate into an optimizer slot; the inner update
    runs UNCONDITIONALLY on the running accumulator, and param/state
    changes are kept only on every k-th step — XLA folds the non-apply
    branch into a no-op select, keeping the step program static."""

    def __init__(self, inner_optimizer: Optimizer, k_steps: int = 2,
                 avg: bool = True):
        self._inner = inner_optimizer
        self.k_steps = max(1, int(k_steps))
        self.avg = avg
        self._probed = set()  # param ids whose slots are materialized
        self._calls = 0       # python-side, for _step_count bookkeeping
        inner_optimizer._global_state.setdefault(
            "grad_merge_step", jnp.asarray(0, jnp.int32))

    def step(self):
        from ...core.tensor import Tensor

        inner = self._inner
        k = self.k_steps
        if k == 1:
            return inner.step()
        store = inner._accumulators.setdefault("grad_merge", {})
        cnt = inner._global_state["grad_merge_step"] + 1
        inner._global_state["grad_merge_step"] = cnt
        apply_now = (cnt % k) == 0

        # accumulate this microbatch's grads; `params` covers every param
        # with EITHER a fresh grad or a pending accumulator, so a param
        # whose grad is absent in the apply-step microbatch (conditional
        # branch) still gets its merged gradient applied rather than
        # silently wiped.
        all_params = [p for p, _, _ in inner._collect_params_grads()]
        for p in all_params:
            if p.grad is not None:
                g = p.grad._value
                acc = store.get(id(p))
                store[id(p)] = g if acc is None else acc + g
        params = [p for p in all_params if id(p) in store]

        # Eager fast path: outside a trace apply_now is concrete, so the
        # snapshot/update/blend dance (which runs the full inner update and
        # copies every slot just to discard them on non-apply steps) is
        # unnecessary — accumulate-and-return, or apply the merged grad.
        if not isinstance(cnt, jax.core.Tracer):
            self._calls += 1
            if not bool(apply_now):
                return
            denom = float(k) if self.avg else 1.0
            for p in params:
                p.grad = Tensor(store[id(p)] / denom, stop_gradient=True)
            inner.step()
            # zero-fill (not clear): the traced path keeps keys alive, so a
            # param that stops receiving grads still gets zero-grad updates
            # (weight decay etc.) — eager must match compiled semantics.
            for pid in list(store):
                store[pid] = jnp.zeros_like(store[pid])
            return

        # materialize the inner optimizer's slots BEFORE snapshotting —
        # slots born inside a non-apply step would dodge the blend and
        # keep partial-gradient pollution.  Probing runs the full update
        # rule on a zero probe, so do it once per param.
        for p in params:
            if id(p) in self._probed:
                continue
            names, inits = inner._probe_accumulators(p)
            for name, init in zip(names, inits):
                inner._accumulators.setdefault(name, {}).setdefault(
                    id(p), init)
            self._probed.add(id(p))

        # snapshot (COPIES: the inner update rules donate their param and
        # slot buffers — a reference would be a deleted array afterwards),
        # run the inner update on the accumulated grad, then blend
        def _copy(v):
            return v.copy() if hasattr(v, "copy") else v

        snap_p = [(p, _copy(p._value)) for p in params]
        snap_acc = {name: {pid: _copy(v) for pid, v in s.items()}
                    for name, s in inner._accumulators.items()
                    if name != "grad_merge"}
        snap_global = {key: _copy(v)
                       for key, v in inner._global_state.items()}
        denom = float(k) if self.avg else 1.0
        for p in params:
            p.grad = Tensor(store[id(p)] / denom, stop_gradient=True)
        inner.step()
        # python-side step counter: count only real (every k-th) updates,
        # so state_dict()['@step'] matches the device-side blended counter.
        # Adjust the innermost base Optimizer — a wrapper (e.g. LocalSGD)
        # between us and it owns an unrelated _step_count of its own.
        self._calls += 1
        if self._calls % k != 0:
            base = inner
            while not isinstance(base, Optimizer) and hasattr(base, "_inner"):
                base = base._inner
            base._step_count = max(0, base._step_count - 1)
        for p, old in snap_p:
            p._value = jnp.where(apply_now, p._value, old)
        for name, snap in snap_acc.items():
            cur = inner._accumulators[name]
            for pid, old in snap.items():
                if pid in cur and getattr(cur[pid], "shape", None) == \
                        getattr(old, "shape", ()):
                    cur[pid] = jnp.where(apply_now, cur[pid], old)
        for key, old in snap_global.items():
            if key == "grad_merge_step":
                continue
            try:
                inner._global_state[key] = jnp.where(
                    apply_now, inner._global_state[key], old)
            except Exception:
                pass
        for pid in list(store):
            store[pid] = jnp.where(apply_now,
                                   jnp.zeros_like(store[pid]), store[pid])

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def __getattr__(self, name):
        if name.startswith("_inner") or name.startswith("__"):
            raise AttributeError(name)
        return getattr(self._inner, name)
