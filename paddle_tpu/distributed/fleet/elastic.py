"""Elastic training manager (reference:
python/paddle/distributed/fleet/elastic/manager.py:130 ElasticManager —
etcd node watches, lease heartbeats, scale up/down detection, trainer
relaunch; and launch/controllers/master.py rendezvous).

TPU-native twist: the rendezvous/heartbeat KV is our own native TCPStore
(distributed/store.py, C++ server) instead of etcd — one fewer external
service, same watch/lease semantics.  Each node registers under
``nodes/<host>``, refreshes a heartbeat lease in a daemon thread, and the
manager detects membership changes (dead lease or new registration) to
drive scale-up/down: on change it rebuilds the endpoint list and invokes
the restart callback (which reloads from checkpoint, reference behavior).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional

__all__ = ["ElasticStatus", "ElasticManager"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Membership + heartbeat over a TCPStore; decides when the job must
    restart (membership changed) or hold (within min/max nodes)."""

    def __init__(self, store, node_id: str, np_range=(1, 1),
                 heartbeat_interval: float = 2.0,
                 lease_ttl: float = 6.0,
                 on_restart: Optional[Callable[[List[str]], None]] = None):
        self.store = store
        self.node_id = node_id
        self.min_np, self.max_np = (np_range if isinstance(np_range, tuple)
                                    else (np_range, np_range))
        self.heartbeat_interval = heartbeat_interval
        self.lease_ttl = lease_ttl
        self.on_restart = on_restart
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_members: List[str] = []

    # ---------------------------------------------------------- membership
    def register(self):
        self.store.set(f"nodes/{self.node_id}",
                       json.dumps({"ts": time.time()}))
        # Registry is append-only via the store's atomic counter: slot n is
        # claimed with add() (no lost updates under concurrent joins),
        # then written once.  Readers scan slots 1..count and dedupe.
        slot = self.store.add("nodes/__count__", 1)
        self.store.set(f"nodes/__reg__/{slot}", self.node_id)
        members = self._alive_members()
        self._last_members = members
        self._thread = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True)
        self._thread.start()

    def _heartbeat(self):
        self.store.set(f"nodes/{self.node_id}",
                       json.dumps({"ts": time.time()}))

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            try:
                self._heartbeat()
            except Exception:
                pass
            self._stop.wait(self.heartbeat_interval)

    def _alive_members(self) -> List[str]:
        """Nodes whose lease is fresher than lease_ttl, discovered through
        the append-only slot registry (atomic-counter claims, so concurrent
        registrations are never lost)."""
        now = time.time()
        count = int(self.store.add("nodes/__count__", 0))
        index = set()
        for slot in range(1, count + 1):
            try:
                nid = self.store.get(f"nodes/__reg__/{slot}", wait=False)
                if nid:
                    index.add(nid.decode() if isinstance(nid, bytes) else nid)
            except Exception:
                continue
        index.add(self.node_id)
        alive = []
        for nid in sorted(index):
            try:
                info = json.loads(self.store.get(f"nodes/{nid}", wait=False))
                if now - float(info["ts"]) <= self.lease_ttl:
                    alive.append(nid)
            except Exception:
                continue
        return alive

    # ------------------------------------------------------------- control
    def watch(self) -> str:
        """One scheduling decision (reference: manager.py watch loop)."""
        members = self._alive_members()
        if members != self._last_members:
            self._last_members = members
            if len(members) < self.min_np:
                return ElasticStatus.HOLD  # wait for nodes to come back
            if self.on_restart is not None:
                self.on_restart(members)
            return ElasticStatus.RESTART
        if not (self.min_np <= len(members) <= self.max_np):
            return ElasticStatus.HOLD
        return ElasticStatus.COMPLETED

    def exit(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        try:
            self.store.delete_key(f"nodes/{self.node_id}")
        except Exception:
            pass
