"""fleet.utils (reference: python/paddle/distributed/fleet/utils/ —
recompute, LocalFS/HDFSClient file helpers used by checkpoint paths)."""
from __future__ import annotations

import os
import shutil

from ...recompute import recompute, recompute_sequential  # noqa: F401


class LocalFS:
    """Local filesystem client (reference: fleet/utils/fs.py LocalFS) —
    the subset the checkpoint paths use."""

    def ls_dir(self, path):
        dirs, files = [], []
        if not os.path.exists(path):
            return dirs, files  # reference LocalFS returns empty lists
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()


class HDFSClient:  # pragma: no cover - no HDFS in a TPU pod's image
    """Parity stub: HDFS is a PS-era dependency (SURVEY declares the PS
    stack out of scope); checkpointing uses orbax/GCS-style paths."""

    def __init__(self, hadoop_home=None, configs=None):
        raise NotImplementedError(
            "HDFS is not available; use LocalFS or a mounted filesystem")


class DistributedInfer:
    """PS-mode inference helper (reference: fleet/utils/ps_util.py
    DistributedInfer — rewrites a training program's distributed-lookup
    ops into local lookups and pulls sparse tables to the worker).

    TPU-native: there is no parameter server holding shards of the
    embedding — tables live in (sharded) device memory and lookups are
    already local gathers under GSPMD — so the program transform is the
    identity.  The class keeps the reference's call protocol so PS-era
    driver scripts run unchanged."""

    def __init__(self, main_program=None, startup_program=None):
        self.origin_main_program = main_program
        self.origin_startup_program = startup_program

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        # reference: runs startup + pulls sparse params from the PS.
        # Here startup already materialized every table on device.
        if self.origin_startup_program is not None:
            exe.run(self.origin_startup_program)
        if dirname is not None:
            from ... import io as _io  # noqa: F401  (load path parity)

    def get_dist_infer_program(self):
        return self.origin_main_program
