"""Fleet: the distributed training front end.

Reference: python/paddle/distributed/fleet/base/fleet_base.py (init:206,
distributed_optimizer:880, distributed_model:937) + DistributedStrategy
(distributed_strategy.py:109 over distributed_strategy.proto).

TPU-native: fleet.init builds the hybrid device mesh from
strategy.hybrid_configs; distributed_model/distributed_optimizer install
GSPMD shardings (params already annotated by parallel layers; optimizer
state inherits or ZeRO-shards them).  The manual NCCL group plumbing of the
reference collapses into mesh construction.
"""
from __future__ import annotations

from typing import Optional

from ...core.tensor import Tensor
from ..env import get_rank, get_world_size
from ..mesh import (CommunicateTopology, HybridCommunicateGroup, fleet_mesh,
                    get_hybrid_communicate_group, get_mesh)
from .distributed_strategy import DistributedStrategy
from .meta_optimizers import DGCMomentum, LocalSGDOptimizer  # noqa: F401
from . import elastic  # noqa: F401
from . import metrics  # noqa: F401
from . import utils  # noqa: F401

_FLEET = None


class _Fleet:
    def __init__(self):
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self.strategy = strategy or DistributedStrategy()
        hc = self.strategy.hybrid_configs
        import jax

        # hybrid degrees are WORLD degrees: the global device count is
        # the intended denominator here, not the per-process one
        n = len(jax.devices())  # lint-tpu: disable=H112
        dp = hc.get("dp_degree", 1) or 1
        mp = hc.get("mp_degree", 1) or 1
        pp = hc.get("pp_degree", 1) or 1
        sh = hc.get("sharding_degree", 1) or 1
        sp = hc.get("sep_degree", 1) or 1
        ep = hc.get("ep_degree", 1) or 1
        prod = dp * mp * pp * sh * sp * ep
        if prod != n and prod == 1:
            dp = n  # default pure-DP over all chips
        fleet_mesh(dp_degree=dp, mp_degree=mp, pp_degree=pp,
                   sharding_degree=sh, sp_degree=sp, ep_degree=ep)
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "model"], [dp, pp, sh, mp])
        self.hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self


fleet = _Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return fleet.init(role_maker, is_collective, strategy)


def shutdown():
    """Tear down fleet state: the global mesh/HCG AND the fleet singleton
    (reference: fleet_base.py stop_worker).  Leaves the process ready for
    a fresh fleet.init with a different topology."""
    from ..mesh import reset_mesh

    reset_mesh()
    fleet.strategy = None
    fleet.hcg = None
    fleet._is_initialized = False


def get_hybrid_communicate_group_():
    return fleet.hcg


def distributed_model(model):
    """Wrap a model for hybrid-parallel execution (reference dispatches to
    PipelineParallel/TensorParallel/ShardingParallel wrappers,
    fleet_base.py:1042-1067).  With GSPMD the wrappers are annotation
    passes:
      - parallel layers already carry mp shardings
      - sharding_degree>1 → FSDP-style param sharding on the sharding axis
      - pp_degree>1 → the model must be a PipelineLayer (stage stacking)
    """
    from ..mesh import get_mesh

    hcg = fleet.hcg or get_hybrid_communicate_group()
    mesh = get_mesh()
    if mesh is None:
        return model

    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        stage = 3
        if fleet.strategy is not None:
            stage = int((fleet.strategy.sharding_configs or {}).get(
                "stage", 3))
        apply_group_sharding(model, mesh, stage=stage)
    from ..pipeline import PipelineLayer, PipelineParallel

    if (isinstance(model, PipelineLayer)
            and mesh.shape.get("pp", 1) > 1):
        # reference fleet_base.py:1042: pp models wrap in PipelineParallel,
        # whose train_batch runs the compiled 1F1B schedule
        return PipelineParallel(model, hcg, fleet.strategy)
    return model


def _zero_spec(p, mesh):
    """Largest divisible axis of p over the 'sharding' mesh axis."""
    from jax.sharding import PartitionSpec

    deg = mesh.shape.get("sharding", 1)
    for axis, size in enumerate(p.shape):
        if size % deg == 0 and size >= deg:
            spec = [None] * len(p.shape)
            spec[axis] = "sharding"
            return PartitionSpec(*spec)
    return PartitionSpec()


def _canonical_zero_spec(name, p, mesh):
    """The canonical SpecLayout role spec mapped onto this mesh's axes
    (fsdp→'sharding', tp→'mp'), restricted to axes the mesh has and
    dims they divide.  None when the name has no role or nothing of the
    role spec survives restriction — callers fall back to _zero_spec."""
    from jax.sharding import PartitionSpec

    from ..sharding import SpecLayout, llama_param_role

    role = llama_param_role(name)
    if role is None:
        return None
    layout = SpecLayout(data_axis="dp", fsdp_axis="sharding",
                        tp_axis="mp", batch_axis="dp")
    spec = layout.spec_for_role(role)
    if not tuple(spec):
        return PartitionSpec()  # deliberately replicated role (norm)
    entries = []
    for dim, entry in enumerate(tuple(spec)):
        axes = (entry,) if isinstance(entry, str) else tuple(entry or ())
        deg = 1
        for a in axes:
            deg *= int(mesh.shape.get(a, 0) or 0)
        if (not axes or any(a not in mesh.shape for a in axes)
                or dim >= len(p.shape) or int(p.shape[dim]) % deg != 0):
            entries.append(None)
        else:
            entries.append(entry)
    if all(e is None for e in entries):
        return None
    return PartitionSpec(*entries)


def apply_group_sharding(model, mesh, stage=3):
    """ZeRO stages over the 'sharding' mesh axis (reference:
    sharding_optimizer.py stage 1, group_sharded_stage2.py,
    group_sharded_stage3.py:58).

    stage 1: optimizer state sharded (params+grads replicated) — slots are
      device_put onto the spec by distributed_optimizer's accumulator hook.
    stage 2: + gradients sharded (the reference's reduce-scatter becomes a
      sharding constraint applied to each grad at step time; the SPMD
      partitioner emits all-reduce + partition slice — slot updates run at
      shard shape — and the TPU/GPU backend pipelines merge that pair into
      reduce-scatter; HLO-verified in TestZeROStages
      test_zero_comm_lowering_in_hlo).
    stage 3: + parameters sharded (the reference's on-demand allgather +
      release hooks become compiler-scheduled GSPMD gathers).
    """
    from jax.sharding import PartitionSpec

    from ..sharding import get_sharding_spec, mark_sharding

    for name, p in model.named_parameters():
        if get_sharding_spec(p) is not None:
            continue  # e.g. mp-annotated parallel layers keep their spec
        spec = None
        if stage >= 2:
            # 'os_g'/'p_g_os' route through the canonical SpecLayout
            # (fsdp→'sharding', tp→'mp') so grads and stage-3 params land
            # on the SAME layout the mesh executor / shardplan validate;
            # non-llama names keep the largest-divisible-dim heuristic
            spec = _canonical_zero_spec(name, p, mesh)
        if spec is None:
            spec = _zero_spec(p, mesh)
        p._zero_opt_spec = spec  # stage >= 1: shard the slots
        if stage >= 2:
            p._zero_grad_spec = spec
        if stage >= 3:
            mark_sharding(p, spec)
        else:
            # params stay REPLICATED but must live on the mesh, else the
            # compiled step is a single-device program and the slot/grad
            # shardings above never materialize.
            mark_sharding(p, PartitionSpec())


# round-1 name, kept for compatibility
def _apply_zero3_sharding(model, mesh):
    apply_group_sharding(model, mesh, stage=3)


def _pin_slot_shardings(optimizer):
    """ZeRO stage >= 1: re-constrain param-shaped optimizer slots onto
    their sharding spec after the update, and params onto THEIR declared
    spec.  GSPMD would otherwise pick layouts freely — dissolving the slot
    partition (m_new = f(m_sharded, g_replicated) → replicated) or,
    conversely, leaking the slot sharding onto stage-1/2 params that must
    stay replicated."""
    import jax
    from jax.sharding import NamedSharding

    from ..mesh import get_mesh
    from ..sharding import get_sharding_spec

    mesh = get_mesh()
    if mesh is None:
        return
    params = {id(p): p for p, _, _ in optimizer._collect_params_grads()}
    for p in params.values():
        pspec = get_sharding_spec(p)
        if pspec is None or not isinstance(p._value, jax.core.Tracer):
            continue
        try:
            p._value = jax.lax.with_sharding_constraint(
                p._value, NamedSharding(mesh, pspec))
        except Exception as e:
            import warnings

            warnings.warn(f"could not pin param sharding {pspec}: {e}")
    for store in optimizer._accumulators.values():
        for pid, arr in list(store.items()):
            p = params.get(pid)
            spec = getattr(p, "_zero_opt_spec", None) if p is not None \
                else None
            if (spec is None or not hasattr(arr, "shape")
                    or tuple(arr.shape) != tuple(p.shape)):
                continue
            sh = NamedSharding(mesh, spec)
            try:
                # NB: hasattr(tracer, "addressable_shards") raises
                # ConcretizationTypeError (not AttributeError) — test the
                # type, don't probe the attribute.
                if isinstance(arr, jax.core.Tracer):
                    store[pid] = jax.lax.with_sharding_constraint(arr, sh)
                else:
                    store[pid] = jax.device_put(arr, sh)
            except Exception as e:
                import warnings

                warnings.warn(
                    f"could not pin optimizer-slot sharding {spec}: {e}")


def distributed_optimizer(optimizer, strategy=None):
    """Wrap the optimizer (reference: HybridParallelOptimizer —
    dygraph_optimizer/hybrid_parallel_optimizer.py:170).  Accumulator slots
    inherit each parameter's sharding; with sharding_degree>1 the slots
    shard even when params don't (ZeRO-1)."""
    strategy = strategy or fleet.strategy
    if strategy is not None and getattr(strategy, "dgc", False):
        from ...optimizer.optimizer import Momentum
        from .meta_optimizers import DGCMomentum

        if isinstance(optimizer, Momentum) \
                and not isinstance(optimizer, DGCMomentum):
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                weight_decay=optimizer._weight_decay or None,
                use_nesterov=optimizer._use_nesterov,
                multi_precision=optimizer._multi_precision,
                **(strategy.dgc_configs or {}))
        elif not isinstance(optimizer, DGCMomentum):
            import warnings

            warnings.warn("strategy.dgc only applies to Momentum optimizers "
                          f"(got {type(optimizer).__name__}); ignored — "
                          "matching the reference DGCOptimizer restriction")
    if strategy is not None and getattr(strategy, "lars", False):
        from ...optimizer.optimizer import LarsMomentum, Momentum

        if type(optimizer) is Momentum:
            optimizer = LarsMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                **(getattr(strategy, "lars_configs", None) or {}))
        elif not isinstance(optimizer, LarsMomentum):
            import warnings

            warnings.warn("strategy.lars only applies to Momentum optimizers "
                          f"(got {type(optimizer).__name__}); ignored — "
                          "matching the reference LarsOptimizer restriction")
    optimizer._is_distributed = True
    orig_add = optimizer._add_accumulator

    def _add_accumulator(name, param, **kwargs):
        import jax
        from jax.sharding import NamedSharding

        from ..mesh import get_mesh
        from ..sharding import get_sharding_spec

        arr = orig_add(name, param, **kwargs)
        mesh = get_mesh()
        # ZeRO stage 1/2: slots shard over the 'sharding' axis even when
        # the param itself stays replicated (reference
        # sharding_optimizer.py opt-state partition) — so the opt-state
        # spec takes priority over the param's own (replicated) spec.
        spec = getattr(param, "_zero_opt_spec", None)
        if spec is None:
            spec = get_sharding_spec(param)
        if mesh is None:
            return arr
        try:
            if spec is not None and arr.shape == tuple(param.shape):
                sh = NamedSharding(mesh, spec)
                if isinstance(arr, jax.core.Tracer):
                    arr = jax.lax.with_sharding_constraint(arr, sh)
                else:
                    arr = jax.device_put(arr, sh)
                optimizer._accumulators[name][id(param)] = arr
        except Exception:
            pass
        return arr

    optimizer._add_accumulator = _add_accumulator

    orig_step = optimizer.step

    def _step():
        # ZeRO stage 2: constrain grads onto the sharding axis before the
        # update (the reference's reduce-scatter grad placement,
        # group_sharded_stage2.py) — under jit GSPMD turns the grad
        # reduction into reduce-scatter + sharded update.
        from ..sharding import shard_tensor

        for p, _, _ in optimizer._collect_params_grads():
            spec = getattr(p, "_zero_grad_spec", None)
            if spec is not None and p.grad is not None:
                p.grad = shard_tensor(p.grad, placements=spec)
        out = orig_step()
        _pin_slot_shardings(optimizer)
        return out

    optimizer.step = _step
    # gradient_merge wraps the base optimizer; localsgd goes OUTERMOST so
    # its sync schedule counts whole train-loop steps and its own counters
    # are never touched by GradientMerge's step-count bookkeeping.
    if strategy is not None and getattr(strategy, "gradient_merge", False):
        from .meta_optimizers import GradientMergeOptimizer

        cfg = strategy.gradient_merge_configs or {}
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    if strategy is not None and getattr(strategy, "localsgd", False):
        from .meta_optimizers import LocalSGDOptimizer

        optimizer = LocalSGDOptimizer(optimizer,
                                      **(strategy.localsgd_configs or {}))
    return optimizer


def get_rank_():
    return get_rank()


worker_index = get_rank
worker_num = get_world_size


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective


class Role:
    """reference: fleet/base/role_maker.py:28."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class UtilBase:
    """Cross-worker convenience collectives (reference:
    fleet/base/util_factory.py UtilBase — there over Gloo comm_world
    handles; here over the XLA/store-backed collective layer, so the
    comm_world argument selects nothing and is accepted for parity)."""

    def __init__(self):
        self.role_maker = None

    def _set_role_maker(self, role_maker):
        self.role_maker = role_maker

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from ...core.tensor import to_tensor
        from ..collective import all_reduce as _ar
        from ..collective import ReduceOp

        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        t = to_tensor(np.asarray(input))
        _ar(t, op=op)
        return t.numpy()

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _barrier

        _barrier()

    def all_gather(self, input, comm_world="worker"):
        import numpy as np

        from ...core.tensor import to_tensor
        from ..collective import all_gather as _ag

        out = []
        _ag(out, to_tensor(np.asarray(input)))
        return [o.numpy() for o in out]

    def get_file_shard(self, files):
        """Contiguous near-even split of `files` for this worker
        (reference util_factory.py:207 — first `remainder` workers get
        one extra file)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file need to be read")
        rank, world = worker_index(), worker_num()
        per, rem = divmod(len(files), world)
        begin = rank * per + min(rank, rem)
        return files[begin:begin + per + (1 if rank < rem else 0)]

    def print_on_rank(self, message, rank_id):
        if get_rank() == rank_id:
            print(message)


# reference exposes the class as fleet.Fleet and a shared util instance
Fleet = _Fleet
util = UtilBase()

from . import data_generator  # noqa: E402,F401
from . import dataset as fleet_dataset  # noqa: E402
from .data_generator import (  # noqa: E402,F401
    MultiSlotDataGenerator, MultiSlotStringDataGenerator)
from .dataset import DatasetBase, InMemoryDataset, QueueDataset  # noqa: E402,F401
