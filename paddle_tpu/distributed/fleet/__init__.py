"""Fleet: the distributed training front end.

Reference: python/paddle/distributed/fleet/base/fleet_base.py (init:206,
distributed_optimizer:880, distributed_model:937) + DistributedStrategy
(distributed_strategy.py:109 over distributed_strategy.proto).

TPU-native: fleet.init builds the hybrid device mesh from
strategy.hybrid_configs; distributed_model/distributed_optimizer install
GSPMD shardings (params already annotated by parallel layers; optimizer
state inherits or ZeRO-shards them).  The manual NCCL group plumbing of the
reference collapses into mesh construction.
"""
from __future__ import annotations

from typing import Optional

from ...core.tensor import Tensor
from ..env import get_rank, get_world_size
from ..mesh import (CommunicateTopology, HybridCommunicateGroup, fleet_mesh,
                    get_hybrid_communicate_group, get_mesh)
from .distributed_strategy import DistributedStrategy
from .meta_optimizers import DGCMomentum, LocalSGDOptimizer  # noqa: F401
from . import elastic  # noqa: F401

_FLEET = None


class _Fleet:
    def __init__(self):
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self.strategy = strategy or DistributedStrategy()
        hc = self.strategy.hybrid_configs
        import jax

        n = len(jax.devices())
        dp = hc.get("dp_degree", 1) or 1
        mp = hc.get("mp_degree", 1) or 1
        pp = hc.get("pp_degree", 1) or 1
        sh = hc.get("sharding_degree", 1) or 1
        sp = hc.get("sep_degree", 1) or 1
        ep = hc.get("ep_degree", 1) or 1
        prod = dp * mp * pp * sh * sp * ep
        if prod != n and prod == 1:
            dp = n  # default pure-DP over all chips
        fleet_mesh(dp_degree=dp, mp_degree=mp, pp_degree=pp,
                   sharding_degree=sh, sp_degree=sp, ep_degree=ep)
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "model"], [dp, pp, sh, mp])
        self.hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self


fleet = _Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return fleet.init(role_maker, is_collective, strategy)


def get_hybrid_communicate_group_():
    return fleet.hcg


def distributed_model(model):
    """Wrap a model for hybrid-parallel execution (reference dispatches to
    PipelineParallel/TensorParallel/ShardingParallel wrappers,
    fleet_base.py:1042-1067).  With GSPMD the wrappers are annotation
    passes:
      - parallel layers already carry mp shardings
      - sharding_degree>1 → FSDP-style param sharding on the sharding axis
      - pp_degree>1 → the model must be a PipelineLayer (stage stacking)
    """
    from ..mesh import get_mesh
    from ..sharding import mark_sharding
    from jax.sharding import PartitionSpec

    hcg = fleet.hcg or get_hybrid_communicate_group()
    mesh = get_mesh()
    if mesh is None:
        return model

    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        _apply_zero3_sharding(model, mesh)
    return model


def _apply_zero3_sharding(model, mesh):
    """ZeRO-3/FSDP: shard every unannotated parameter's largest divisible
    axis over the 'sharding' mesh axis (reference GroupShardedStage3
    partitions params by rank, group_sharded_stage3.py:58 — GSPMD makes the
    gather/release compiler-scheduled)."""
    from jax.sharding import PartitionSpec

    from ..sharding import get_sharding_spec, mark_sharding

    deg = mesh.shape.get("sharding", 1)
    for _, p in model.named_parameters():
        if get_sharding_spec(p) is not None:
            continue
        placed = False
        for axis, size in enumerate(p.shape):
            if size % deg == 0 and size >= deg:
                spec = [None] * len(p.shape)
                spec[axis] = "sharding"
                mark_sharding(p, PartitionSpec(*spec))
                placed = True
                break
        if not placed:
            mark_sharding(p, PartitionSpec())


def distributed_optimizer(optimizer, strategy=None):
    """Wrap the optimizer (reference: HybridParallelOptimizer —
    dygraph_optimizer/hybrid_parallel_optimizer.py:170).  Accumulator slots
    inherit each parameter's sharding; with sharding_degree>1 the slots
    shard even when params don't (ZeRO-1)."""
    strategy = strategy or fleet.strategy
    if strategy is not None and getattr(strategy, "dgc", False):
        from ...optimizer.optimizer import Momentum
        from .meta_optimizers import DGCMomentum

        if isinstance(optimizer, Momentum) \
                and not isinstance(optimizer, DGCMomentum):
            optimizer = DGCMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                weight_decay=optimizer._weight_decay or None,
                use_nesterov=optimizer._use_nesterov,
                multi_precision=optimizer._multi_precision,
                **(strategy.dgc_configs or {}))
        elif not isinstance(optimizer, DGCMomentum):
            import warnings

            warnings.warn("strategy.dgc only applies to Momentum optimizers "
                          f"(got {type(optimizer).__name__}); ignored — "
                          "matching the reference DGCOptimizer restriction")
    if strategy is not None and getattr(strategy, "lars", False):
        from ...optimizer.optimizer import LarsMomentum, Momentum

        if type(optimizer) is Momentum:
            optimizer = LarsMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                **(getattr(strategy, "lars_configs", None) or {}))
        elif not isinstance(optimizer, LarsMomentum):
            import warnings

            warnings.warn("strategy.lars only applies to Momentum optimizers "
                          f"(got {type(optimizer).__name__}); ignored — "
                          "matching the reference LarsOptimizer restriction")
    optimizer._is_distributed = True
    orig_add = optimizer._add_accumulator

    def _add_accumulator(name, param, **kwargs):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ..mesh import get_mesh
        from ..sharding import get_sharding_spec

        arr = orig_add(name, param, **kwargs)
        mesh = get_mesh()
        spec = get_sharding_spec(param)
        if mesh is None:
            return arr
        try:
            is_concrete = hasattr(arr, "addressable_shards")
            if spec is not None and is_concrete:
                arr = jax.device_put(arr, NamedSharding(mesh, spec))
                optimizer._accumulators[name][id(param)] = arr
        except Exception:
            pass
        return arr

    optimizer._add_accumulator = _add_accumulator
    if strategy is not None and getattr(strategy, "localsgd", False):
        from .meta_optimizers import LocalSGDOptimizer

        optimizer = LocalSGDOptimizer(optimizer,
                                      **(strategy.localsgd_configs or {}))
    return optimizer


def get_rank_():
    return get_rank()


worker_index = get_rank
worker_num = get_world_size


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
