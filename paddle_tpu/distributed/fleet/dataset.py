"""PS-mode datasets (reference:
python/paddle/distributed/fleet/dataset/dataset.py — DatasetBase /
QueueDataset / InMemoryDataset over the C++ MultiSlotDataFeed).

TPU-native shape: the C++ DataFeed/channel machinery collapses into a
Python record pipeline (the heavy lifting on TPU is the infeed, which
``paddle_tpu.io.DataLoader`` / DeviceLoader already own).  These classes
keep the reference's FILE PROTOCOL — MultiSlot text, one ``<n> <v>...``
group per slot per line, optionally produced by piping each file through
``pipe_command`` (a data_generator script) — and yield padded numpy
batches ready for Executor feed or DataLoader wrapping.
"""
from __future__ import annotations

import random
import shlex
import subprocess
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["DatasetBase", "QueueDataset", "InMemoryDataset"]


def _var_name(v):
    return v if isinstance(v, str) else getattr(v, "name", str(v))


def _var_is_float(v):
    dt = str(getattr(v, "dtype", "int64")).lower()
    return "float" in dt


class DatasetBase:
    """Common config surface (reference DatasetBase.init/_set_* methods)."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.use_var: List = []
        self.pipe_command = "cat"
        self.input_type = 0
        self.filelist: List[str] = []

    def init(self, batch_size=1, thread_num=1, use_var=(), pipe_command="cat",
             input_type=0, fs_name="", fs_ugi="", download_cmd="cat",
             **kwargs):
        self.batch_size = batch_size
        self.thread_num = thread_num
        self.use_var = list(use_var)
        self.pipe_command = pipe_command
        self.input_type = input_type
        return self

    def set_filelist(self, filelist: Sequence[str]):
        self.filelist = list(filelist)

    # -- record parsing ----------------------------------------------------
    def _read_lines(self, path: str):
        if self.pipe_command and self.pipe_command != "cat":
            # reference semantics: every file is piped through the user's
            # data_generator command; its stdout is the MultiSlot text
            with open(path, "rb") as fin:
                proc = subprocess.Popen(
                    shlex.split(self.pipe_command), stdin=fin,
                    stdout=subprocess.PIPE, text=True)
                try:
                    yield from proc.stdout
                finally:
                    proc.stdout.close()
                    proc.wait()
        else:
            with open(path) as f:
                yield from f

    def _parse_line(self, line: str) -> Optional[List[np.ndarray]]:
        toks = line.split()
        if not toks:
            return None
        out = []
        pos = 0
        for v in (self.use_var or [None]):
            if pos >= len(toks):
                return None  # short line: drop the record, like DataFeed
            n = int(toks[pos])
            vals = toks[pos + 1:pos + 1 + n]
            pos += 1 + n
            if v is None or _var_is_float(v):
                out.append(np.asarray([float(x) for x in vals], np.float32))
            else:
                out.append(np.asarray([int(x) for x in vals], np.int64))
        return out

    def _records(self):
        for path in self.filelist:
            for line in self._read_lines(path):
                rec = self._parse_line(line)
                if rec is not None:
                    yield rec

    def _batch(self, records: List[List[np.ndarray]]) -> Dict[str, np.ndarray]:
        """Pad each slot to the batch max length; LoD becomes (data, lens)."""
        names = [_var_name(v) for v in (self.use_var or [])] or [
            f"slot_{i}" for i in range(len(records[0]))]
        out: Dict[str, np.ndarray] = {}
        for i, name in enumerate(names):
            cols = [r[i] for r in records]
            width = max(len(c) for c in cols)
            arr = np.zeros((len(cols), width), cols[0].dtype)
            for j, c in enumerate(cols):
                arr[j, :len(c)] = c
            out[name] = arr
            out[name + "@len"] = np.asarray([len(c) for c in cols], np.int64)
        return out

    def _batches_of(self, it):
        buf = []
        for rec in it:
            buf.append(rec)
            if len(buf) == self.batch_size:
                yield self._batch(buf)
                buf = []
        if buf:
            yield self._batch(buf)


class QueueDataset(DatasetBase):
    """Streaming dataset: records flow straight from file (through
    pipe_command) to batches, nothing retained (reference QueueDataset)."""

    def __iter__(self):
        return self._batches_of(self._records())


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference InMemoryDataset: beam-style
    load_into_memory / local_shuffle / global_shuffle / release_memory)."""

    def __init__(self):
        super().__init__()
        self._memory: List = []
        self._shuffled = 0

    def init(self, **kwargs):
        super().init(**kwargs)
        return self

    def update_settings(self, **kwargs):
        for k, v in kwargs.items():
            if k == "use_var":
                self.use_var = list(v)
            elif hasattr(self, k):
                setattr(self, k, v)

    def load_into_memory(self):
        self._memory = list(self._records())

    # preload is synchronous here: there is no C++ channel to overlap with
    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        random.shuffle(self._memory)
        self._shuffled = len(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Deterministic cross-rank partition: every rank shuffles the full
        record set with the SAME seed, then keeps its hash slice — the
        collective-free equivalent of the reference's shuffle service."""
        rank, world = 0, 1
        if fleet is not None:
            rank = getattr(fleet, "worker_index", lambda: 0)()
            world = getattr(fleet, "worker_num", lambda: 1)()
        else:
            from ..env import get_rank, get_world_size

            rank, world = get_rank(), get_world_size()
        rng = random.Random(2021)
        order = list(range(len(self._memory)))
        rng.shuffle(order)
        self._memory = [self._memory[i] for i in order[rank::max(world, 1)]]
        self._shuffled = len(self._memory)

    def release_memory(self):
        self._memory = []
        self._shuffled = 0

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None) -> int:
        return self._shuffled

    def slots_shuffle(self, slots):
        """Shuffle the VALUES of the named slots across records (the
        reference's feature-importance ablation tool)."""
        names = [_var_name(v) for v in self.use_var]
        for s in slots:
            if s not in names:
                continue
            i = names.index(s)
            col = [r[i] for r in self._memory]
            random.shuffle(col)
            for r, c in zip(self._memory, col):
                r[i] = c

    def __iter__(self):
        return self._batches_of(iter(self._memory))
