"""Fleet data generators (reference:
python/paddle/distributed/fleet/data_generator/data_generator.py).

The parameter-server data pipeline's user-side half: a subclass implements
``generate_sample(line)`` returning an iterator over
``[(slot_name, [feasign, ...]), ...]`` samples; ``run_from_stdin`` streams
raw lines in and emits the MultiSlotDataFeed text protocol
(``<ids_num> <id> <id> ...`` per slot) that QueueDataset / InMemoryDataset
parse back into batches."""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """User hook: return a zero-arg iterator over parsed samples."""
        raise NotImplementedError(
            "generate_sample must be implemented by the subclass")

    def generate_batch(self, samples):
        """User hook: batch-level post-processing; default passthrough."""

        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def _drain(self, samples, out):
        for sample in self.generate_batch(samples)():
            out.write(self._gen_str(sample))

    def run_from_memory(self):
        """Emit samples produced by generate_sample(None) to stdout."""
        batch = []
        it = self.generate_sample(None)
        for parsed in it():
            if parsed is None:
                continue
            batch.append(parsed)
            if len(batch) == self.batch_size_:
                self._drain(batch, sys.stdout)
                batch = []
        if batch:
            self._drain(batch, sys.stdout)

    def run_from_stdin(self):
        """Parse stdin lines with generate_sample, emit datafeed text."""
        batch = []
        for line in sys.stdin:
            it = self.generate_sample(line)
            for parsed in it():
                if parsed is None:
                    continue
                batch.append(parsed)
                if len(batch) == self.batch_size_:
                    self._drain(batch, sys.stdout)
                    batch = []
        if batch:
            self._drain(batch, sys.stdout)


def _check_slots(line):
    if isinstance(line, zip):
        line = list(line)
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of generate_sample must be a list or tuple of "
            "(name, values) pairs, e.g. [('words', [1926, 8, 17]), "
            "('label', [1])]")
    return line


class MultiSlotDataGenerator(DataGenerator):
    """Numeric feasigns; records per-slot dtype in proto_info (uint64 for
    ints, float for floats — the reference's protofile contract)."""

    def _gen_str(self, line):
        line = _check_slots(line)
        parts = []
        proto = []
        for name, elements in line:
            parts.append(str(len(elements)))
            dtype = "uint64"
            for v in elements:
                if isinstance(v, float):
                    dtype = "float"
                parts.append(str(v))
            proto.append((name, dtype))
        if self._proto_info is None:
            self._proto_info = proto
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Pre-stringified feasigns: fastest path, no type promotion."""

    def _gen_str(self, line):
        line = _check_slots(line)
        parts = []
        for _name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(elements)
        return " ".join(parts) + "\n"
