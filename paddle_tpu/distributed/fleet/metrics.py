"""fleet.metrics (reference:
python/paddle/distributed/fleet/metrics/metric.py — global metric
reduction over a gloo/NCCL allreduce: sum/max/min/auc/mae/rmse/acc).

TPU-native: the reduction rides the normal collective path (XLA over the
mesh inside shard_map; identity in a single-controller world, where the
global view already includes every shard).
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor, to_tensor
from ..collective import ReduceOp, all_reduce


def _reduce(value, op, force_float=False):
    """Reduce a COPY — the caller's running counter must not be
    overwritten with the global value (all_reduce works in place)."""
    from ..env import get_world_size

    if isinstance(value, Tensor):
        # device (possibly traced) values reduce as-is — this is the
        # shard_map/jit path where all_reduce lowers to psum; copy so the
        # caller's tensor is not rebound to the global value
        t = Tensor(value._value)
        all_reduce(t, op=op)
        return t
    arr = np.asarray(value, np.float64)
    # host-side integral counters reduce as integers: float32 loses
    # exactness above 2^24, which real instance counts exceed.  The
    # choice keys on the INPUT dtype (rank-invariant), never the values.
    in_dtype = np.asarray(value).dtype
    integral = not force_float and np.issubdtype(in_dtype, np.integer)
    if get_world_size() <= 1:
        return to_tensor(arr.astype(np.int64) if integral else arr)
    t = to_tensor(arr.astype(np.int64 if integral else np.float32))
    all_reduce(t, op=op)
    return t


def sum(metric):  # noqa: A001 - reference uses the builtin-shadowing name
    return _reduce(metric, ReduceOp.SUM)


def max(metric):  # noqa: A001
    return _reduce(metric, ReduceOp.MAX)


def min(metric):  # noqa: A001
    return _reduce(metric, ReduceOp.MIN)


def mean(metric):
    # AVG divides — integer reduction would truncate
    return _reduce(metric, ReduceOp.AVG, force_float=True)


def acc(correct, total):
    """Global accuracy: sum(correct) / sum(total) across ranks."""
    c = _reduce(correct, ReduceOp.SUM)
    t = _reduce(total, ReduceOp.SUM)
    return to_tensor(np.asarray(c.numpy(), np.float64)
                     / np.maximum(np.asarray(t.numpy(), np.float64), 1))


def mae(abserr, total_ins_num):
    """Global mean absolute error from per-rank absolute-error sums."""
    e = _reduce(abserr, ReduceOp.SUM)
    n = _reduce(total_ins_num, ReduceOp.SUM)
    return to_tensor(np.asarray(e.numpy(), np.float64)
                     / np.maximum(np.asarray(n.numpy(), np.float64), 1))


def rmse(sqrerr, total_ins_num):
    e = _reduce(sqrerr, ReduceOp.SUM)
    n = _reduce(total_ins_num, ReduceOp.SUM)
    return to_tensor(np.sqrt(np.asarray(e.numpy(), np.float64)
                             / np.maximum(np.asarray(n.numpy(), np.float64),
                                          1)))


def auc(stat_pos, stat_neg):
    """Global AUC from per-rank positive/negative threshold histograms
    (the reference's confusion-matrix formulation)."""
    pos = np.asarray(_reduce(stat_pos, ReduceOp.SUM).numpy(), np.float64)
    neg = np.asarray(_reduce(stat_neg, ReduceOp.SUM).numpy(), np.float64)
    # walk thresholds high->low accumulating tp/fp area
    area = 0.0
    tp = fp = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_tp = tp + pos[i]
        new_fp = fp + neg[i]
        area += (new_fp - fp) * (tp + new_tp) / 2.0
        tp, fp = new_tp, new_fp
    if tp == 0 or fp == 0:
        return to_tensor(np.asarray(0.5, np.float64))
    return to_tensor(np.asarray(area / (tp * fp), np.float64))
