"""DistributedStrategy (reference: python/paddle/distributed/fleet/base/
distributed_strategy.py:109 over distributed_strategy.proto) — one typed
config tree; the proto becomes a plain dataclass-style object."""
from __future__ import annotations

from typing import Any, Dict


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (proto: HybridConfig:51)
        self.hybrid_configs: Dict[str, Any] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1,
        }
        # amp (proto AMPConfig:58)
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0, "use_pure_fp16": False,
            "use_bf16": True, "custom_white_list": [],
            "custom_black_list": [],
        }
        # recompute (proto RecomputeConfig:26)
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        # ZeRO sharding (proto ShardingConfig:32).  Default stage is 3
        # (full FSDP-style param sharding): deviates from the reference
        # static sharding_optimizer's stage-1 default on purpose — GSPMD
        # makes stage 3 the natural TPU formulation, and sharding_degree>1
        # with no explicit stage has meant ZeRO-3 here since round 1.
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {
            "sharding_degree": 1, "stage": 3, "offload": False,
            "segment_broadcast_MB": 32,
        }
        # gradient merge (proto:84)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # pipeline (proto PipelineConfig)
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "schedule_mode": "1F1B",
                                 "micro_batch_size": 1}
        # misc toggles kept for parity
        self.lamb = False
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001, "lars_weight_decay": 0.0005}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "sparsity": 0.999}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.cudnn_exhaustive_search = False
        self.heter_ccl_mode = False
        self.a_sync = False
        self.a_sync_configs: Dict[str, Any] = {}

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in self.__dict__.items():
            lines.append(f"  {k}={v},")
        return "\n".join(lines) + ")"
