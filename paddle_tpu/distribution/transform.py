# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.distribution.transform (reference:
python/paddle/distribution/ transform APIs of the 2.x line; the 2022
snapshot ships the Distribution zoo in python/paddle/distribution/ and the
transform family completes it).

Bijective tensor transforms with log-det-jacobian tracking, composable via
ChainTransform and lifted over batch dims by IndependentTransform; used by
TransformedDistribution.  All math is jnp (XLA-fusable, TPU-safe).
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    return Tensor(jnp.asarray(x))


def _sum_rightmost(x, n):
    """Sum over the rightmost n dims (no-op for n <= 0)."""
    if n <= 0:
        return x
    return jnp.sum(x, axis=tuple(range(-n, 0)))


class Transform:
    """Base transform; subclasses implement _forward/_inverse and
    _forward_log_det_jacobian (per-element)."""

    _domain_event_dim = 0
    _codomain_event_dim = 0

    def forward(self, x):
        return _t(self._forward(_v(x)))

    def inverse(self, y):
        return _t(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return _t(self._forward_log_det_jacobian(_v(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _v(y)
        return _t(-self._forward_log_det_jacobian(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| (not bijective; inverse returns the positive branch)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    """y = exp(x)."""

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power (x > 0)."""

    def __init__(self, power):
        self.power = _v(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    """y = sigmoid(x)."""

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x)."""

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    """Compose transforms left-to-right: y = tN(...t1(x))."""

    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)
        dims = [(t._domain_event_dim, t._codomain_event_dim)
                for t in self.transforms] or [(0, 0)]
        self._domain_event_dim = max(d for d, _ in dims)
        self._codomain_event_dim = max(c for _, c in dims)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        # Track the evolving event rank through the chain (reference:
        # python/paddle/distribution/transform.py:535): each transform's
        # per-element jacobian is summed over the rightmost
        # (event_rank - t._domain_event_dim) dims before accumulating, so
        # mixed event-dim chains (e.g. Affine then StickBreaking) reduce to
        # a consistent shape instead of broadcast-adding wrongly.
        total = 0.0
        event_rank = self._domain_event_dim
        for t in self.transforms:
            j = t._forward_log_det_jacobian(x)
            total = total + _sum_rightmost(
                j, event_rank - t._domain_event_dim)
            x = t._forward(x)
            event_rank += t._codomain_event_dim - t._domain_event_dim
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class IndependentTransform(Transform):
    """Reinterpret the rightmost `reinterpreted_batch_rank` dims as event
    dims: the log-det sums over them."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._domain_event_dim = (base._domain_event_dim
                                  + self.reinterpreted_batch_rank)
        self._codomain_event_dim = (base._codomain_event_dim
                                    + self.reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        j = self.base._forward_log_det_jacobian(x)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(j, axis=axes)


class ReshapeTransform(Transform):
    """Reshape the event part of the tensor."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_dim = len(self.in_event_shape)
        self._codomain_event_dim = len(self.out_event_shape)
        if int(jnp.prod(jnp.array(self.in_event_shape or (1,)))) != int(
                jnp.prod(jnp.array(self.out_event_shape or (1,)))):
            raise ValueError("in/out event shapes must have equal size")

    def _batch(self, x, event_shape):
        n = len(event_shape)
        return x.shape[:x.ndim - n] if n else x.shape

    def _forward(self, x):
        return x.reshape(self._batch(x, self.in_event_shape)
                         + self.out_event_shape)

    def _inverse(self, y):
        return y.reshape(self._batch(y, self.out_event_shape)
                         + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros(self._batch(x, self.in_event_shape), x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (not bijective; inverse = log)."""

    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("SoftmaxTransform is not bijective")


class StackTransform(Transform):
    """Apply a different transform to each slice along `axis`."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        self.transforms = list(transforms)
        self.axis = axis

    def _apply(self, x, method):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, method)(jnp.squeeze(p, self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._apply(x, "_forward")

    def _inverse(self, y):
        return self._apply(y, "_inverse")

    def _forward_log_det_jacobian(self, x):
        return self._apply(x, "_forward_log_det_jacobian")


class StickBreakingTransform(Transform):
    """Unconstrained R^{K-1} -> K-simplex via stick breaking."""

    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)],
                               axis=-1)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype),
             jnp.cumprod(1 - z, axis=-1)], axis=-1)
        return zpad * one_minus

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]], axis=-1)
        z = y[..., :-1] / rest
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        # d y_i / d x_i factors: sigmoid' * remaining stick
        log_stick = jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,), x.dtype),
             jnp.cumsum(jnp.log1p(-z), axis=-1)[..., :-1]], axis=-1)
        return jnp.sum(-jax.nn.softplus(-xo) - jax.nn.softplus(xo)
                       + log_stick, axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
