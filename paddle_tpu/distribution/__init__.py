# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.distribution (reference: python/paddle/distribution/ — ~10
distributions + kl_divergence + transforms)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor
from ..ops import random as rnd

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Exponential", "Gamma", "Laplace",
           "LogNormal", "Multinomial", "Gumbel", "Geometric", "Poisson",
           "kl_divergence", "register_kl"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _shape(self, shape):
        if isinstance(shape, int):
            return (shape,)
        return tuple(shape)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=(), seed=0):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.normal(key, shp) * self.scale._value
                      + self.loc._value)

    def log_prob(self, value):
        def _lp(v, loc, scale):
            var = scale ** 2
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return apply("normal_log_prob", _lp, _t(value), self.loc, self.scale)

    def entropy(self):
        def _ent(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return apply("normal_entropy", _ent, self.scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return apply("sq", jnp.square, self.scale)

    @property
    def stddev(self):
        return self.scale


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.base = Normal(loc, scale)
        super().__init__(self.base.batch_shape)

    def sample(self, shape=()):
        from ..ops.math import exp

        return exp(self.base.sample(shape))

    def log_prob(self, value):
        def _lp(v, loc, scale):
            logv = jnp.log(v)
            var = scale ** 2
            return -((logv - loc) ** 2) / (2 * var) - jnp.log(scale * v) \
                - 0.5 * math.log(2 * math.pi)
        return apply("lognormal_log_prob", _lp, _t(value), self.base.loc,
                     self.base.scale)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.low._value.shape, self.high._value.shape)))

    def sample(self, shape=(), seed=0):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        u = jax.random.uniform(key, shp)
        return Tensor(self.low._value + u * (self.high._value
                                             - self.low._value))

    def log_prob(self, value):
        def _lp(v, lo, hi):
            inside = (v >= lo) & (v <= hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply("uniform_log_prob", _lp, _t(value), self.low, self.high)

    def entropy(self):
        def _ent(lo, hi):
            return jnp.log(hi - lo)
        return apply("uniform_entropy", _ent, self.low, self.high)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is not None:
            self.logits = apply("log", lambda p: jnp.log(
                jnp.clip(p, 1e-30, None)), _t(probs))
        else:
            self.logits = _t(logits)
        super().__init__(tuple(self.logits._value.shape[:-1]))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.categorical(
            key, self.logits._value.astype(jnp.float32),
            shape=shp if shp else None).astype(jnp.int64))

    def probs(self, value):
        """Probabilities of the given category indices (reference
        categorical.py:266 — a METHOD taking `value`, not the full
        softmax; 1-D logits gather all entries, batched logits take
        along the last axis)."""
        return self.prob(value)

    def log_prob(self, value):
        def _lp(lg, v):
            logp = jax.nn.log_softmax(lg, -1)
            v = v.astype(jnp.int32)
            # reference categorical.py probs(): 1-D logits gather ALL
            # value entries from the one distribution (output
            # value.shape); batched logits take a 1-D value broadcast
            # across distributions, or an aligned value along axis -1
            if logp.ndim == 1:
                return logp[v.reshape(-1)].reshape(v.shape)
            if v.ndim == 1:
                vb = v.reshape((1,) * (logp.ndim - 1) + (-1,))
                return jnp.take_along_axis(
                    logp, jnp.broadcast_to(
                        vb, logp.shape[:-1] + (v.shape[0],)), -1)
            if v.ndim == logp.ndim - 1:
                # aligned per-batch index gather ([B,T] value over
                # [B,T,K] logits — the per-token case)
                return jnp.take_along_axis(logp, v[..., None], -1)[..., 0]
            return jnp.take_along_axis(logp, v, -1)
        return apply("categorical_log_prob", _lp, self.logits, _t(value))

    def entropy(self):
        def _ent(lg):
            logp = jax.nn.log_softmax(lg, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)
        return apply("categorical_entropy", _ent, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs_t = _t(probs)
        else:
            self.probs_t = apply("sigmoid", jax.nn.sigmoid, _t(logits))
        super().__init__(tuple(self.probs_t._value.shape))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            key, self.probs_t._value, shp).astype(jnp.float32))

    def log_prob(self, value):
        def _lp(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply("bernoulli_log_prob", _lp, self.probs_t, _t(value))

    def entropy(self):
        def _ent(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply("bernoulli_entropy", _ent, self.probs_t)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.alpha._value.shape, self.beta._value.shape)))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(key, self.alpha._value,
                                      self.beta._value, shp))

    def log_prob(self, value):
        def _lp(v, a, b):
            from jax.scipy.special import betaln

            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - betaln(a, b)
        return apply("beta_log_prob", _lp, _t(value), self.alpha, self.beta)

    @property
    def mean(self):
        def _m(a, b):
            return a / (a + b)
        return apply("beta_mean", _m, self.alpha, self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        super().__init__(tuple(self.concentration._value.shape[:-1]),
                         tuple(self.concentration._value.shape[-1:]))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(key, self.concentration._value,
                                           shp if shp else None))

    def log_prob(self, value):
        def _lp(v, c):
            from jax.scipy.special import gammaln

            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1))
        return apply("dirichlet_log_prob", _lp, _t(value), self.concentration)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate._value.shape))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.exponential(key, shp) / self.rate._value)

    def log_prob(self, value):
        def _lp(v, r):
            return jnp.log(r) - r * v
        return apply("exponential_log_prob", _lp, _t(value), self.rate)

    @property
    def mean(self):
        return apply("recip", jnp.reciprocal, self.rate)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.concentration._value.shape, self.rate._value.shape)))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.gamma(key, self.concentration._value, shp)
                      / self.rate._value)

    def log_prob(self, value):
        def _lp(v, a, r):
            from jax.scipy.special import gammaln

            return a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - gammaln(a)
        return apply("gamma_log_prob", _lp, _t(value), self.concentration,
                     self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.laplace(key, shp) * self.scale._value
                      + self.loc._value)

    def log_prob(self, value):
        def _lp(v, loc, b):
            return -jnp.abs(v - loc) / b - jnp.log(2 * b)
        return apply("laplace_log_prob", _lp, _t(value), self.loc, self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.gumbel(key, shp) * self.scale._value
                      + self.loc._value)

    def log_prob(self, value):
        def _lp(v, loc, b):
            z = (v - loc) / b
            return -(z + jnp.exp(-z)) - jnp.log(b)
        return apply("gumbel_log_prob", _lp, _t(value), self.loc, self.scale)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = _t(probs)
        super().__init__(tuple(self.probs_t._value.shape))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.geometric(key, self.probs_t._value,
                                           shp).astype(jnp.float32))

    def log_prob(self, value):
        def _lp(p, v):
            return (v - 1) * jnp.log1p(-p) + jnp.log(p)
        return apply("geometric_log_prob", _lp, self.probs_t, _t(value))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate._value.shape))

    def sample(self, shape=()):
        key = rnd.next_key()
        shp = self._shape(shape) + self.batch_shape
        return Tensor(jax.random.poisson(key, self.rate._value,
                                         shp).astype(jnp.float32))

    def log_prob(self, value):
        def _lp(v, r):
            from jax.scipy.special import gammaln

            return v * jnp.log(r) - r - gammaln(v + 1)
        return apply("poisson_log_prob", _lp, _t(value), self.rate)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = total_count
        self.probs_t = _t(probs)
        super().__init__(tuple(self.probs_t._value.shape[:-1]),
                         tuple(self.probs_t._value.shape[-1:]))

    def sample(self, shape=()):
        key = rnd.next_key()
        n = self.total_count
        cat = jax.random.categorical(
            key, jnp.log(jnp.clip(self.probs_t._value, 1e-30, None)),
            shape=self._shape(shape) + self.batch_shape + (n,))
        k = self.probs_t._value.shape[-1]
        return Tensor(jax.nn.one_hot(cat, k).sum(-2))

    def log_prob(self, value):
        def _lp(v, p):
            from jax.scipy.special import gammaln

            n = jnp.sum(v, -1)
            return (gammaln(n + 1) - jnp.sum(gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(jnp.clip(p, 1e-30, None)), -1))
        return apply("multinomial_log_prob", _lp, _t(value), self.probs_t)


# ------------------------------------------------------------- KL registry
_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def _kl(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return apply("kl_normal", _kl, p.loc, p.scale, q.loc, q.scale)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def _kl(pl, ql):
        logp = jax.nn.log_softmax(pl, -1)
        logq = jax.nn.log_softmax(ql, -1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), -1)
    return apply("kl_categorical", _kl, p.logits, q.logits)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def _kl(plo, phi, qlo, qhi):
        return jnp.log((qhi - qlo) / (phi - plo))
    return apply("kl_uniform", _kl, p.low, p.high, q.low, q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def _kl(pp, qp):
        pp = jnp.clip(pp, 1e-7, 1 - 1e-7)
        qp = jnp.clip(qp, 1e-7, 1 - 1e-7)
        return pp * jnp.log(pp / qp) + (1 - pp) * jnp.log(
            (1 - pp) / (1 - qp))
    return apply("kl_bernoulli", _kl, p.probs_t, q.probs_t)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    def _kl(pr, qr):
        ratio = qr / pr
        return ratio - jnp.log(ratio) - 1
    return apply("kl_exponential", _kl, p.rate, q.rate)


def _sum_rightmost(v, k):
    return jnp.sum(v, axis=tuple(range(-k, 0))) if k > 0 else v


class TransformedDistribution(Distribution):
    """Distribution of y = T(x), x ~ base (reference: the 2.x
    paddle.distribution.TransformedDistribution API).  Event-dim
    bookkeeping follows the torch/paddle convention: a transform's
    log-det-jacobian comes back with its codomain event dims already
    reduced, and the remaining event dims are summed here."""

    def __init__(self, base: Distribution, transforms):
        from .transform import ChainTransform, Transform

        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out = self._chain.forward_shape(shape)
        event_dim = max([len(base.event_shape)]
                        + [t._codomain_event_dim for t in self.transforms])
        cut = len(out) - event_dim
        super().__init__(batch_shape=out[:cut], event_shape=out[cut:])

    def sample(self, shape=()):
        x = self.base.sample(self._shape(shape))
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(self._shape(shape))
        return self._chain.forward(x)

    def log_prob(self, value):
        from ..core.dispatch import apply

        # event_dim evolution is static (no tensor dependence)
        event_dims = []
        event_dim = len(self.event_shape)
        for t in reversed(self.transforms):
            event_dims.append(event_dim)
            event_dim += t._domain_event_dim - t._codomain_event_dim

        def _lp(y):
            acc = None
            for t, ed in zip(reversed(self.transforms), event_dims):
                x = t._inverse(y)
                ildj = _sum_rightmost(-t._forward_log_det_jacobian(x),
                                      ed - t._codomain_event_dim)
                acc = ildj if acc is None else acc + ildj
                y = x
            return y, acc

        x, ildj = apply("transformed_invert", _lp, value)
        base_lp = self.base.log_prob(x)
        extra = event_dim - len(self.base.event_shape)
        if extra > 0:
            def _sum(v):
                return _sum_rightmost(v, extra)

            base_lp = apply("sum_event_dims", _sum, base_lp)
        return base_lp + ildj


class Independent(Distribution):
    """Reinterpret the rightmost `reinterpreted_batch_rank` batch dims of
    `base` as event dims: log_prob sums over them."""

    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        k = self.reinterpreted_batch_rank
        super().__init__(batch_shape=bshape[:len(bshape) - k],
                         event_shape=bshape[len(bshape) - k:]
                         + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        from ..core.dispatch import apply

        lp = self.base.log_prob(value)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))

        def _sum(v):
            return jnp.sum(v, axis=axes)

        return apply("independent_sum", _sum, lp)

    def entropy(self):
        from ..core.dispatch import apply

        ent = self.base.entropy()
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))

        def _sum(v):
            return jnp.sum(v, axis=axes)

        return apply("independent_sum", _sum, ent)


from . import transform  # noqa: E402,F401
from .transform import (AbsTransform, AffineTransform, ChainTransform,  # noqa: E402,F401
                        ExpTransform, IndependentTransform, PowerTransform,
                        ReshapeTransform, SigmoidTransform, SoftmaxTransform,
                        StackTransform, StickBreakingTransform, TanhTransform,
                        Transform)

__all__ += ["TransformedDistribution", "Independent", "Transform",
            "AbsTransform", "AffineTransform", "ChainTransform",
            "ExpTransform", "IndependentTransform", "PowerTransform",
            "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
            "StackTransform", "StickBreakingTransform", "TanhTransform"]


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference:
    python/paddle/distribution/exponential_family.py): subclasses expose
    natural parameters + log-normalizer; entropy falls out via the
    Bregman identity H = A(eta) - <eta, grad A(eta)> - E[log h(x)]."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        import jax
        import jax.numpy as jnp

        nat = [jnp.asarray(p._value if hasattr(p, "_value") else p)
               for p in self._natural_parameters]
        # elementwise over the batch: grad of the SUMMED log-normalizer
        # gives per-element partials because A is applied elementwise
        grads = jax.grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = self._log_normalizer(*nat) - self._mean_carrier_measure
        for p, g in zip(nat, grads):
            ent = ent - p * g
        return Tensor(jnp.asarray(ent))
