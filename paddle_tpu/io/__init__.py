# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.io: Dataset / DataLoader (reference: python/paddle/fluid/reader.py:273
DataLoader, fluid/dataloader/ worker.py + batch_sampler.py + dataset.py).

Multiprocess workers feed batches through queues; a background prefetch
thread keeps a buffer ahead of the consumer — the host-side half of the
infeed pipeline (the reference's buffered_reader.cc double-buffering is the
device half; on TPU, jax device_put overlap covers it).
"""
from __future__ import annotations

import itertools
import math
import os
import queue as queue_mod
import time
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ConcatDataset", "ChainDataset", "Subset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "DataLoader",
    "DeviceLoader", "get_worker_info", "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            sample = ds[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(
            len(d) for d in self.datasets))

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = np.searchsorted(self.cumulative_sizes, idx, side="right")
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx else 0
        return self.datasets[ds_idx][idx - prev]

    def __len__(self):
        return self.cumulative_sizes[-1]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        total = len(dataset)
        lengths = [int(math.floor(total * l)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != len(dataset):
        raise ValueError("lengths must sum to dataset size")
    perm = np.random.permutation(len(dataset))
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    python/paddle/fluid/dataloader/batch_sampler.py:168)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None \
            else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack a list of samples into batched Tensors."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return to_tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return batch


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, seed, arena=None):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed(seed)
    while True:
        item = index_queue.get()
        if item is None:
            break
        task_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            batch = collate_fn(samples)
            batch = _to_numpy_tree(batch)
            if arena is not None:
                from .shm import pack_tree

                batch = pack_tree(batch, arena)
            data_queue.put((task_id, batch, None))
        except Exception as e:  # propagate worker errors
            data_queue.put((task_id, None, e))


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return obj.numpy()
    if isinstance(obj, (list, tuple)):
        return [_to_numpy_tree(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    return obj


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return to_tensor(obj)
    if isinstance(obj, (list, tuple)):
        return [_to_tensor_tree(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    return obj


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)
            if batch_size is None:
                self.batch_sampler = None  # no auto-batching

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multiprocess()

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_single(self):
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_multiprocess(self):
        import multiprocessing as mp

        # fork is only safe while JAX has no live non-CPU backend: the TPU /
        # tunnel clients own threads+locks that deadlock a forked child (the
        # reference hits the same with CUDA contexts and also switches to
        # spawn-style workers).  spawn children are exec-fresh and read the
        # parent env at start() time; worker payloads (dataset, collate_fn)
        # must then be picklable.
        method = os.environ.get("PT_DATALOADER_START_METHOD")
        if method is None:
            unsafe = False
            try:
                from jax._src import xla_bridge as _xb

                unsafe = any(k != "cpu"
                             for k in getattr(_xb, "_backends", {}))
            except Exception:
                pass
            method = "spawn" if unsafe else "fork"
        ctx = mp.get_context(method)
        index_queues = [ctx.SimpleQueue() for _ in range(self.num_workers)]
        data_queue = ctx.Queue()
        arena = None
        workers = []
        # Keep worker processes off the accelerator: they produce host
        # batches only, and a fresh child dialing the TPU client would race
        # the parent for the chip.  (fork children never re-init JAX, so the
        # env is only mutated for exec-fresh start methods.)
        saved_platforms = os.environ.get("JAX_PLATFORMS")
        try:
            if method != "fork":
                os.environ["JAX_PLATFORMS"] = "cpu"
            # Shared-memory transport (reference: use_shared_memory + the
            # mmap allocator): fork workers inherit the arena mapping;
            # spawn workers re-attach by name when unpickling it.
            if self.use_shared_memory:
                from . import shm

                if shm.shm_available():
                    try:
                        arena = shm.ShmArena()
                    except Exception:
                        arena = None
            for wid in range(self.num_workers):
                w = ctx.Process(
                    target=_worker_loop,
                    args=(self.dataset, index_queues[wid], data_queue,
                          self.collate_fn, wid, self.num_workers,
                          np.random.randint(0, 2 ** 31), arena),
                    daemon=True)
                w.start()
                workers.append(w)
        except BaseException:
            for w in workers:
                w.terminate()
            if arena is not None:
                arena.destroy()
            raise
        finally:
            if method != "fork":
                if saved_platforms is not None:
                    os.environ["JAX_PLATFORMS"] = saved_platforms
                else:
                    os.environ.pop("JAX_PLATFORMS", None)

        try:
            batches = list(self.batch_sampler)
            n_tasks = len(batches)
            # dispatch up to prefetch_factor batches per worker ahead
            next_task = 0
            inflight = 0
            results = {}
            want = 0
            max_inflight = self.num_workers * self.prefetch_factor
            while next_task < n_tasks and inflight < max_inflight:
                index_queues[next_task % self.num_workers].put(
                    (next_task, batches[next_task]))
                next_task += 1
                inflight += 1
            while want < n_tasks:
                while want not in results:
                    # Liveness-aware get: a worker that dies before putting
                    # (unpicklable payload, failed arena attach, OOM-kill)
                    # must raise here, not hang the training loop.
                    # timeout in (None, 0) = no deadline (reference
                    # convention); the dead-worker liveness check still
                    # runs every second either way.
                    deadline = (time.monotonic() + self.timeout
                                if self.timeout else None)
                    while True:
                        try:
                            task_id, data, err = data_queue.get(timeout=1)
                            break
                        except queue_mod.Empty:
                            dead = [w for w in workers if not w.is_alive()]
                            if dead:
                                raise RuntimeError(
                                    "DataLoader worker (pid "
                                    f"{dead[0].pid}) exited unexpectedly "
                                    f"with code {dead[0].exitcode}")
                            if (deadline is not None
                                    and time.monotonic() > deadline):
                                raise RuntimeError(
                                    f"DataLoader timed out after "
                                    f"{self.timeout}s waiting for a batch")
                    if err is not None:
                        raise err
                    results[task_id] = data
                    inflight -= 1
                    if next_task < n_tasks:
                        index_queues[next_task % self.num_workers].put(
                            (next_task, batches[next_task]))
                        next_task += 1
                        inflight += 1
                data = results.pop(want)
                if arena is not None:
                    from .shm import unpack_tree

                    data = unpack_tree(data, arena)
                yield _to_tensor_tree(data)
                want += 1
        finally:
            for q in index_queues:
                q.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            if arena is not None:
                arena.destroy()


class DeviceLoader:
    """Device-prefetching wrapper: the host->HBM infeed half of the
    reference's double-buffered reader (buffered_reader.cc keeps N batches
    resident on device ahead of compute).  Wrap any iterable of batches;
    each batch is jax.device_put'd (optionally with a sharding) while the
    previous one is being consumed, so transfers overlap the step.

        for x, y in DeviceLoader(loader, buffer_size=2):
            loss = train_step(x, y)
    """

    def __init__(self, loader, buffer_size=2, sharding=None, device=None):
        self.loader = loader
        self.buffer_size = max(1, int(buffer_size))
        self.sharding = sharding
        self.device = device

    def _place(self, batch):
        import jax

        from ..core.tensor import Tensor

        target = self.sharding or self.device

        def put(v):
            raw = v._value if isinstance(v, Tensor) else v
            arr = jax.device_put(raw, target) if target is not None \
                else jax.device_put(raw)
            return Tensor(arr)

        if isinstance(batch, tuple) and hasattr(batch, "_fields"):
            return type(batch)(*map(put, batch))  # namedtuple
        if isinstance(batch, (list, tuple)):
            return type(batch)(put(v) for v in batch)
        if isinstance(batch, dict):
            return {k: put(v) for k, v in batch.items()}
        return put(batch)

    def __iter__(self):
        from collections import deque

        buf = deque()
        it = iter(self.loader)
        try:
            for _ in range(self.buffer_size):
                buf.append(self._place(next(it)))
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            try:
                # enqueue the next transfer BEFORE yielding: device_put is
                # async, so it overlaps the consumer's compute
                buf.append(self._place(next(it)))
            except StopIteration:
                pass
            yield out

    def __len__(self):
        try:
            return len(self.loader)
        except TypeError:
            raise TypeError(
                "DeviceLoader wraps a len-less iterable; iterate instead")
