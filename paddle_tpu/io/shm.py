"""Shared-memory tensor transport for multiprocess DataLoader workers.

Python face of paddle_tpu/core/native/shm_arena.cc (TPU-native equivalent of
the reference's mmap shared-memory DataLoader tensors —
paddle/fluid/memory/allocation/mmap_allocator.cc + fluid/dataloader
worker.py `use_shared_memory`).  Workers memcpy ndarray payloads into a
POSIX shm arena created by the parent before fork; only (offset, shape,
dtype) travels through the result queue, so large batches skip pickling.

Fork-only: the child inherits the parent's mapping, so the raw arena handle
(a heap pointer) stays valid across the process boundary.
"""
from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

_lib = None
_lib_failed = False


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        from ..core.native.build import load_native

        lib = load_native("shm_arena", extra_flags=("-lrt",))
        lib.shm_arena_create.restype = ctypes.c_void_p
        lib.shm_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_arena_attach.restype = ctypes.c_void_p
        lib.shm_arena_attach.argtypes = [ctypes.c_char_p]
        lib.shm_arena_alloc.restype = ctypes.c_uint64
        lib.shm_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_alloc2.restype = ctypes.c_uint64
        lib.shm_arena_alloc2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.shm_arena_free.restype = ctypes.c_int
        lib.shm_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_ptr.restype = ctypes.c_void_p
        lib.shm_arena_ptr.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_write.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                        ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_read.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_used.restype = ctypes.c_uint64
        lib.shm_arena_used.argtypes = [ctypes.c_void_p]
        lib.shm_arena_capacity.restype = ctypes.c_uint64
        lib.shm_arena_capacity.argtypes = [ctypes.c_void_p]
        lib.shm_arena_generation.restype = ctypes.c_uint32
        lib.shm_arena_generation.argtypes = [ctypes.c_void_p]
        lib.shm_arena_detach.argtypes = [ctypes.c_void_p]
        lib.shm_arena_destroy.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


_UINT64_MAX = 2 ** 64 - 1
_arena_counter = 0

# Leaves smaller than this stay on the pickle path (header overhead wins).
MIN_SHM_BYTES = 4096


@dataclass
class ShmRef:
    """Queue-transportable handle to an array living in the arena."""
    offset: int
    shape: tuple
    dtype: str
    generation: int = 0


class ShmArena:
    """First-fit shm allocator shared parent<->worker processes.

    Forked workers inherit the mapping; spawned/forkserver workers re-attach
    by name via ``__reduce__`` (shm_open of the same POSIX object)."""

    def __init__(self, capacity: int = 256 << 20):
        global _arena_counter
        lib = _load()
        if lib is None:
            raise RuntimeError("native shm_arena unavailable")
        _arena_counter += 1
        self.name = f"/pt_shm_{os.getpid()}_{_arena_counter}".encode()
        self._lib = lib
        self._h = lib.shm_arena_create(self.name, capacity)
        if not self._h:
            raise RuntimeError("shm_arena_create failed")
        self._owner_pid = os.getpid()

    @classmethod
    def _attach(cls, name: bytes) -> "ShmArena":
        lib = _load()
        if lib is None:
            raise RuntimeError("native shm_arena unavailable")
        self = cls.__new__(cls)
        self.name = name
        self._lib = lib
        self._h = lib.shm_arena_attach(name)
        if not self._h:
            raise RuntimeError(f"shm_arena_attach({name!r}) failed")
        self._owner_pid = -1  # attached: never unlink, only detach
        return self

    def __reduce__(self):
        return (ShmArena._attach, (self.name,))

    def put_array(self, arr: np.ndarray) -> Optional[ShmRef]:
        arr = np.ascontiguousarray(arr)
        gen = ctypes.c_uint32(0)
        # generation is sampled under the alloc mutex: race-free against a
        # concurrent crash-reset bumping it between alloc and stamping.
        off = self._lib.shm_arena_alloc2(self._h, arr.nbytes,
                                         ctypes.byref(gen))
        if off == _UINT64_MAX:
            return None  # arena full — caller falls back to pickling
        self._lib.shm_arena_write(self._h, off, arr.ctypes.data, arr.nbytes)
        return ShmRef(off, arr.shape, arr.dtype.str, gen.value)

    def get_array(self, ref: ShmRef, free: bool = True) -> np.ndarray:
        def _check():
            if ref.generation != self._lib.shm_arena_generation(self._h):
                # A worker crashed mid-critical-section and the free list
                # was reset; this ref's bytes may already be reused by a
                # newer allocation.  Never hand back possibly-corrupt data.
                raise RuntimeError(
                    "shm arena was reset after a worker crash; in-flight "
                    "batch lost (allocated under an older generation)")

        _check()
        out = np.empty(ref.shape, dtype=np.dtype(ref.dtype))
        self._lib.shm_arena_read(self._h, ref.offset, out.ctypes.data,
                                 out.nbytes)
        _check()  # a reset DURING the copy would have bumped it
        if free:
            self._lib.shm_arena_free(self._h, ref.offset)
        return out

    def free(self, ref: ShmRef):
        self._lib.shm_arena_free(self._h, ref.offset)

    def used_bytes(self) -> int:
        return self._lib.shm_arena_used(self._h)

    def destroy(self):
        if self._h:
            if os.getpid() == self._owner_pid:
                self._lib.shm_arena_destroy(self._h, self.name)
            else:
                self._lib.shm_arena_detach(self._h)
            self._h = None

    def __del__(self):  # best-effort cleanup
        try:
            self.destroy()
        except Exception:
            pass


def pack_tree(obj, arena: ShmArena):
    """Replace large ndarray leaves with ShmRefs (worker side)."""
    if isinstance(obj, np.ndarray) and obj.nbytes >= MIN_SHM_BYTES:
        ref = arena.put_array(obj)
        return ref if ref is not None else obj
    if isinstance(obj, (list, tuple)):
        return [pack_tree(v, arena) for v in obj]
    if isinstance(obj, dict):
        return {k: pack_tree(v, arena) for k, v in obj.items()}
    return obj


def unpack_tree(obj, arena: ShmArena):
    """Materialize ShmRefs back to ndarrays, freeing slots (parent side)."""
    if isinstance(obj, ShmRef):
        return arena.get_array(obj, free=True)
    if isinstance(obj, (list, tuple)):
        return [unpack_tree(v, arena) for v in obj]
    if isinstance(obj, dict):
        return {k: unpack_tree(v, arena) for k, v in obj.items()}
    return obj


def shm_available() -> bool:
    return _load() is not None
