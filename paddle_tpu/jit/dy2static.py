# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Minimal AST dy2static pass (VERDICT r3 #7).

Reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py + convert_operators.py — the reference rewrites
EVERY ``if``/``while`` into ``convert_ifelse``/``convert_while_loop``
calls whose runtime helpers pick between Python control flow and the
framework's functional cond/while ops based on whether the predicate is
a Tensor.  This pass does the same for the common cases so reference
scripts with data-dependent ``if tensor:`` / ``while tensor:`` compile
under trace-based ``to_static`` instead of failing at trace time with a
ConcretizationTypeError:

- ``if``/``while`` statements are rewritten into local closures whose
  parameter list is the set of names the bodies assign, called through
  ``_cvt_ifelse`` / ``_cvt_while`` — Python semantics are preserved
  exactly when the predicate is a plain bool, and data-dependent
  predicates lower to ``jit.cond`` / ``jit.while_loop`` (XLA Cond/While).
- A statement is left UNTOUCHED (trace fallback) when the minimal pass
  cannot preserve semantics: ``return``/``break``/``continue`` in a
  body, attribute/subscript stores (object mutation would run at trace
  time for both branches), ``global``/``nonlocal``, or use of a name
  the pass cannot thread through the closure.
- The whole transform silently falls back to the original function when
  source is unavailable (builtins, C, exec), the function closes over
  free variables, or anything else goes wrong — exactly the posture of
  the reference's ``@not_to_static`` escape hatch.
"""
from __future__ import annotations

import ast
import inspect
import textwrap

__all__ = ["convert_function", "_cvt_ifelse", "_cvt_while",
           "_cvt_for_range", "_cvt_not", "_cvt_and", "_cvt_or"]

_HELPERS = "__paddle_tpu_dy2static_helpers__"

# ambient loop bound (stack: nested to_static calls may differ), set by
# to_static(loop_max_trips=N) for the duration of a call: tensor-bound
# while/for-range lower to the BOUNDED differentiable while_loop
# (scan-of-cond) instead of forward-only XLA While — reference scripts
# that train through data-dependent python loops work with one kwarg.
_LOOP_MAX_TRIPS = [None]


def _is_tensorish(x):
    from ..core.tensor import Tensor

    if isinstance(x, Tensor):
        import jax

        return isinstance(x._value, jax.core.Tracer)
    import jax

    return isinstance(x, jax.core.Tracer)


class _Undefined:
    """Placeholder for a carried name with no binding before the control
    statement (reference: dygraph_to_static UndefinedVar).  Reaching one
    at runtime means the user's code read a variable defined in only one
    branch — the same error eager Python would raise, surfaced late."""

    def __repr__(self):
        return "<dy2static undefined variable>"


_UNDEF = _Undefined()


def _is_operand(a):
    """Values that can ride through lax.cond/while operands: tensors,
    arrays, and plain scalars.  Everything else (layers, optimizers,
    modules, strings, _UNDEF) is closed over as a trace-time constant."""
    if a is _UNDEF:
        return False
    from ..core.tensor import Tensor

    return (isinstance(a, Tensor) or hasattr(a, "dtype")
            or isinstance(a, (bool, int, float, complex)))


def _cvt_ifelse(pred, true_fn, false_fn, args, names=(), n_stores=None):
    """Runtime half of the if-rewrite (reference:
    convert_operators.py convert_ifelse).

    The Tensor-predicate path dispatches ONE tape op whose forward is a
    lax.cond over the carried values: lax.cond is jax-differentiable, so
    ``loss.backward()`` through a converted ``if`` reaches every carried
    tensor (a bare jit.cond would return node-less Tensors and silently
    drop the gradient chain).  Non-operand carried values (layers,
    optimizers, modules, _UNDEF placeholders) are closed over as
    trace-time constants; assigned positions always come OUT of the cond
    so both-branch-assigned names work even when undefined before."""
    if n_stores is None:
        n_stores = len(args)
    if _is_tensorish(pred):
        from . import _tape_cond

        in_idx = [i for i, a in enumerate(args) if _is_operand(a)]
        out_idx = sorted(set(in_idx) | set(range(n_stores)))

        def sel(branch):
            def wrapped(*real):
                full = list(args)
                for i, v in zip(in_idx, real):
                    full[i] = v
                out = branch(*full)
                out = out if isinstance(out, tuple) else (out,)
                return tuple(out[i] for i in out_idx)
            return wrapped

        try:
            res_out = _tape_cond(pred, sel(true_fn), sel(false_fn),
                                 [args[i] for i in in_idx],
                                 op_name="dy2st_cond")
        except TypeError as e:
            if "Undefined" not in str(e):
                raise
            undef = [n for n, a in zip(names, args) if a is _UNDEF]
            raise ValueError(
                "dy2static: variable(s) assigned in only one branch of a "
                f"Tensor-predicate if cannot compile to XLA Cond: "
                f"{undef or '<unknown>'}; initialize them before the if "
                "(both branches of a compiled conditional must produce "
                "the same variables)") from e
        res = list(args)
        if not isinstance(res_out, (tuple, list)):
            res_out = (res_out,)
        for i, v in zip(out_idx, res_out):
            res[i] = v
        return tuple(res)
    return true_fn(*args) if pred else false_fn(*args)


def _cvt_while(cond_fn, body_fn, args, names=(), n_stores=None):
    """Runtime half of the while-rewrite (reference:
    convert_operators.py convert_while_loop).  The Tensor-condition path
    lowers to XLA While via jit.while_loop (forward-only: XLA While has
    no reverse-mode); non-operand carried values are closed over."""
    if n_stores is None:
        n_stores = len(args)
    first = cond_fn(*args)
    if _is_tensorish(first):
        _check_store_operands(args, names, n_stores, "while")
        from . import while_loop

        op_idx = [i for i, a in enumerate(args) if _is_operand(a)]

        def merge(real):
            full = list(args)
            for i, v in zip(op_idx, real):
                full[i] = v
            return full

        def c2(*real):
            return cond_fn(*merge(real))

        def b2(*real):
            out = body_fn(*merge(real))
            out = out if isinstance(out, tuple) else (out,)
            return tuple(out[i] for i in op_idx)

        real_out = while_loop(c2, b2, [args[i] for i in op_idx],
                              maximum_trip_count=_LOOP_MAX_TRIPS[-1])
        res = list(args)
        for i, v in zip(op_idx, real_out):
            res[i] = v
        return tuple(res)
    # python-bool loop: reuse `first` — re-evaluating a side-effecting
    # condition (iterator, counter) would silently skip an iteration
    vals = tuple(args)
    cur = first
    while cur:
        out = body_fn(*vals)
        vals = out if isinstance(out, tuple) else (out,)
        cur = cond_fn(*vals)
    return vals


def _raw(x):
    from ..core.tensor import Tensor

    return x._value if isinstance(x, Tensor) else x


def _cvt_not(x):
    """Tensor-aware logical not (reference: convert_operators.py
    convert_logical_not) — used in fabricated break/return guards."""
    if _is_tensorish(x):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        return Tensor(jnp.logical_not(_raw(x)))
    return not x


def _cvt_and(a, b):
    """Tensor-aware logical and (both sides evaluated — fabricated
    conditions only, where the original expression was already
    unconditionally evaluated per iteration)."""
    if _is_tensorish(a) or _is_tensorish(b):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        return Tensor(jnp.logical_and(_raw(a), _raw(b)))
    return a and b


def _cvt_or(a, b):
    if _is_tensorish(a) or _is_tensorish(b):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        return Tensor(jnp.logical_or(_raw(a), _raw(b)))
    return a or b


def _cvt_and_lazy(a, b_thunk):
    """Short-circuiting and for fabricated LOOP conditions: with a plain
    python flag the original test is NOT re-evaluated once the flag is
    set (python `and` semantics); with a traced flag both sides trace
    (XLA evaluates eagerly anyway)."""
    if _is_tensorish(a):
        return _cvt_and(a, b_thunk())
    return a and b_thunk()


def _check_store_operands(args, names, n_stores, kind):
    """Every body-ASSIGNED carried value must be an operand (tensor/array/
    scalar) under a Tensor-condition loop: XLA While needs typed loop
    state, and a non-operand store would be silently DROPPED (the body
    closure only returns operand positions).  _UNDEF means no binding at
    all; None and other trace constants are equally unrepresentable."""
    bad = [names[i] if i < len(names) else f"<arg {i}>"
           for i in range(n_stores) if not _is_operand(args[i])]
    if bad:
        hint = ""
        if _RET in bad or _RETF in bad:
            hint = (" ('__to_static_ret*' entries mean a `return` inside "
                    "this loop: pre-assign the result variable with the "
                    "returned shape/dtype before the loop)")
        raise ValueError(
            f"dy2static {kind} over a Tensor condition: every loop-"
            "carried variable must be initialized to a tensor/scalar "
            f"before the loop (XLA While needs typed loop state): "
            f"{bad}{hint}")


def _range_cond(i, stop, step):
    """Loop-continue predicate for a lowered for-range: ``i < stop`` for
    positive step, ``i > stop`` for negative; sign-folded when the step
    itself is a traced value."""
    if _is_tensorish(step):
        return (i - stop) * step < 0
    return i < stop if step > 0 else i > stop


def _cvt_for_range(start, stop, step, body_fn, prior, args, names=(),
                   n_stores=None):
    """Runtime half of the for-range rewrite (reference:
    convert_operators.py convert_range semantics).

    Plain-int bounds run a REAL python ``for`` — loop-var binding (last
    iterated value; the prior binding survives an empty range), step=0
    ValueError, and iteration order are exactly eager Python's, so
    converting a function that never sees a Tensor bound changes nothing.
    A traced bound lowers to XLA While via jit.while_loop (one
    executable for every trip count; forward-only like the while
    rewrite).  Returns ``(loop_var, *carried)``."""
    if not any(_is_tensorish(v) for v in (start, stop, step)):
        vals = tuple(args)
        i = prior
        for i in range(start, stop, step):
            out = body_fn(i, *vals)
            vals = out if isinstance(out, tuple) else (out,)
        return (i,) + vals
    if not _is_tensorish(step) and step == 0:
        raise ValueError("range() arg 3 must not be zero")
    _check_store_operands(args, names, n_stores or 0, "for-range")
    from . import while_loop

    op_idx = [i for i, a in enumerate(args) if _is_operand(a)]

    def merge(real):
        full = list(args)
        for i, v in zip(op_idx, real):
            full[i] = v
        return full

    def c2(i, *real):
        return _range_cond(i, stop, step)

    def b2(i, *real):
        out = body_fn(i, *merge(real))
        out = out if isinstance(out, tuple) else (out,)
        return (i + step,) + tuple(out[k] for k in op_idx)

    state = while_loop(c2, b2, [start] + [args[i] for i in op_idx],
                       maximum_trip_count=_LOOP_MAX_TRIPS[-1])
    i_fin, real_out = state[0], state[1:]
    res = list(args)
    for i, v in zip(op_idx, real_out):
        res[i] = v
    # loop var after the loop: the last ITERATED value.  i_fin overshoots
    # by one step; with zero trips this leaves start-step, where eager
    # python would keep the prior binding — a data-dependent trip count
    # cannot reproduce that statically, so prefer the arithmetic value.
    return (i_fin - step,) + tuple(res)


class _Unsupported(Exception):
    pass


def _assigned_names(stmts):
    """Names bound by plain Name stores in a statement list (recursing
    into nested ifs/loops but NOT into nested function/class defs)."""
    names = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):  # don't descend
            names.append(node.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            names.append(node.name)

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                names.append(node.id)

    for s in stmts:
        V().visit(s)
    # preserve first-seen order, dedupe; generated helper names
    # (__dy2st_*) are trace-time machinery, never loop/branch state
    seen, out = set(), []
    for n in names:
        if n not in seen and not n.startswith("__dy2st_"):
            seen.add(n)
            out.append(n)
    return out


def _check_supported(stmts):
    """Raise _Unsupported if the bodies contain constructs the minimal
    closure rewrite cannot preserve.  break/continue are only fatal at
    THIS nesting level — inside a nested loop they bind to that loop
    (whose own rewrite or eager execution owns them); this-level ones
    are lowered to flags by the caller BEFORE this check runs."""
    class V(ast.NodeVisitor):
        def __init__(self):
            self.loop_depth = 0

        def visit_Return(self, node):
            raise _Unsupported("return in controlled block")

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = visit_While = _loop

        def visit_Break(self, node):
            if self.loop_depth == 0:
                raise _Unsupported("break in controlled block")

        def visit_Continue(self, node):
            if self.loop_depth == 0:
                raise _Unsupported("continue in controlled block")

        def visit_Global(self, node):
            raise _Unsupported("global in controlled block")

        def visit_Nonlocal(self, node):
            raise _Unsupported("nonlocal in controlled block")

        def visit_FunctionDef(self, node):  # nested defs: opaque, fine
            return

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assign(self, node):
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, (ast.Attribute, ast.Subscript)) \
                            and isinstance(sub.ctx, ast.Store):
                        raise _Unsupported(
                            "attribute/subscript store in controlled "
                            "block (object mutation would run at trace "
                            "time)")
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            if isinstance(node.target, (ast.Attribute, ast.Subscript)):
                raise _Unsupported("attribute/subscript augassign")
            self.generic_visit(node)

    for s in stmts:
        V().visit(s)


def _helper_call(attr, args):
    return ast.Call(
        func=ast.Attribute(value=_name(_HELPERS, ast.Load()),
                           attr=attr, ctx=ast.Load()),
        args=args, keywords=[])


def _assign_const(n, value):
    return ast.Assign(targets=[_name(n, ast.Store())],
                      value=ast.Constant(value=value))


def _has_break_continue(stmts):
    """True if a Break/Continue binds to THIS level (descends ifs and
    try/with, not nested loops or function defs)."""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_For(self, node):
            return

        visit_While = visit_AsyncFor = visit_For

        def visit_FunctionDef(self, node):
            return

        visit_AsyncFunctionDef = visit_ClassDef = visit_FunctionDef

        def visit_Break(self, node):
            found[0] = True

        def visit_Continue(self, node):
            found[0] = True

    for s in stmts:
        V().visit(s)
    return found[0]


import itertools as _itertools

_FRESH_COUNTER = _itertools.count(1)


def _is_range_for(node):
    it = node.iter
    return (not node.orelse and isinstance(node.target, ast.Name)
            and isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range" and not it.keywords
            and 1 <= len(it.args) <= 3
            and not any(isinstance(a, ast.Starred) for a in it.args))


def _lazy_and_flag(flag, test):
    """AST for ``_cvt_and_lazy(_cvt_not(flag), lambda: test)`` — the
    fabricated loop condition used by both the break lowering and the
    return-flag lowering."""
    thunk = ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=test)
    return _helper_call("_cvt_and_lazy", [
        _helper_call("_cvt_not", [_name(flag, ast.Load())]), thunk])


def _range_for_to_while(node):
    """``for i in range(a, b, c): BODY`` → explicit while form (used when
    the body contains break/continue/return, which the _cvt_for_range
    closure cannot carry)::

        __rng1 = a; __rng2 = b; __rng3 = c        # LTR evaluation
        __to_static_it_N__ = __rng1
        while _range_cond(__to_static_it_N__, __rng2, __rng3):
            i = __to_static_it_N__
            __to_static_it_N__ = __to_static_it_N__ + __rng3
            BODY

    The increment precedes BODY so a lowered `continue` (which guards
    only the statements AFTER its flag-set) cannot skip it.  Post-loop
    the loop var holds the last ITERATED value, matching python; on an
    empty range it keeps its prior binding (or stays undefined).
    Raises _Unsupported for non-range fors."""
    if not _is_range_for(node):
        raise _Unsupported("break/continue/return in a non-range for")
    n = next(_FRESH_COUNTER)
    arg_ns = [f"__dy2st_rng{n}_{k}__" for k in range(len(node.iter.args))]
    setup = [ast.Assign(targets=[_name(a, ast.Store())], value=v)
             for a, v in zip(arg_ns, node.iter.args)]
    if len(arg_ns) == 1:
        start, stop, step = ast.Constant(value=0), \
            _name(arg_ns[0], ast.Load()), ast.Constant(value=1)
    elif len(arg_ns) == 2:
        start, stop, step = _name(arg_ns[0], ast.Load()), \
            _name(arg_ns[1], ast.Load()), ast.Constant(value=1)
    else:
        start, stop, step = [_name(a, ast.Load()) for a in arg_ns]
    it_name = f"__to_static_it_{n}__"  # carriable: not a __dy2st_ name
    setup.append(ast.Assign(targets=[_name(it_name, ast.Store())],
                            value=start))
    # seed the loop var too: it is a body store, and a Tensor-bound loop
    # needs a typed pre-loop binding.  DEVIATION (documented in
    # MIGRATING.md "dy2static constraints", flagged by analysis.hazards
    # as H105): a zero-iteration range leaves the loop var at the range
    # start instead of its prior binding / staying unbound
    setup.append(ast.Assign(
        targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
        value=_name(it_name, ast.Load())))
    test = _helper_call("_range_cond",
                        [_name(it_name, ast.Load()), stop, step])
    body = [ast.Assign(targets=[ast.Name(id=node.target.id,
                                         ctx=ast.Store())],
                       value=_name(it_name, ast.Load())),
            ast.Assign(targets=[_name(it_name, ast.Store())],
                       value=ast.BinOp(left=_name(it_name, ast.Load()),
                                       op=ast.Add(), right=step))]
    return setup, ast.While(test=test, body=body + list(node.body),
                            orelse=[])


def _lower_break_continue(stmts, brk, cont):
    """Replace this-level break/continue with flag stores (reference:
    break_continue_transformer.py BreakContinueTransformer).  Statements
    after a flag-setting `if` are guarded by `if not (brk or cont)`;
    statements directly after break/continue are unreachable and
    dropped.  Returns (new_stmts, may_set_flags)."""
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(_assign_const(brk, True))
            return out, True
        if isinstance(s, ast.Continue):
            out.append(_assign_const(cont, True))
            return out, True
        if isinstance(s, ast.If):
            b, fb = _lower_break_continue(s.body, brk, cont)
            o, fo = _lower_break_continue(s.orelse, brk, cont)
            if fb or fo:
                out.append(ast.If(test=s.test, body=b or [ast.Pass()],
                                  orelse=o))
                rest, _ = _lower_break_continue(stmts[i + 1:], brk, cont)
                if rest:
                    guard = _helper_call("_cvt_not", [_helper_call(
                        "_cvt_or", [_name(brk, ast.Load()),
                                    _name(cont, ast.Load())])])
                    out.append(ast.If(test=guard, body=rest, orelse=[]))
                return out, True
            out.append(s)
            continue
        out.append(s)
    return out, False


def _name(n, ctx):
    return ast.Name(id=n, ctx=ctx)


def _undef_guard(n):
    """``try: n  except (NameError, UnboundLocalError): n = _UNDEF`` —
    seeds carried names that have no binding yet."""
    return ast.Try(
        body=[ast.Expr(value=_name(n, ast.Load()))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[_name("NameError", ast.Load()),
                                 _name("UnboundLocalError", ast.Load())],
                           ctx=ast.Load()),
            name=None,
            body=[ast.Assign(
                targets=[_name(n, ast.Store())],
                value=ast.Attribute(
                    value=_name(_HELPERS, ast.Load()),
                    attr="_UNDEF", ctx=ast.Load()))])],
        orelse=[], finalbody=[])


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[_name(n, ast.Load()) for n in names], ctx=ast.Load()))


def _make_fn(fname, params, body, extra_ret):
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])
    return ast.FunctionDef(
        name=fname, args=args, body=body + [extra_ret],
        decorator_list=[], returns=None)


def _loaded_names(nodes):
    """Names read in the given nodes (not descending into nested defs)."""
    names = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            return

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                names.append(node.id)

    for n in nodes:
        V().visit(n)
    return names


class _Rewriter(ast.NodeTransformer):
    def __init__(self, global_names=(), local_names=(), free_names=(),
                 range_shadowed=False):
        self.counter = 0
        self.changed = False
        self.range_shadowed = range_shadowed
        import builtins

        # reads of globals/builtins/free variables stay closed over;
        # LOCALS override (a local named `input` shadowing the builtin
        # must ride as an operand or the gradient chain through the
        # dispatched cond silently breaks).  Free variables must NOT be
        # carried: the rewrite's tuple-assignment would turn them into
        # locals of the converted clone and shadow the closure.
        self._skip = ((set(global_names) | set(dir(builtins))
                       | set(free_names)) - set(local_names))

    def _carried(self, stores, load_nodes):
        """Carried set = assigned names + LOCAL names the bodies read.
        Reads must ride as operands (not closure constants) so the
        gradient chain through the dispatched cond reaches them; global
        and builtin names stay closed over."""
        carried = list(stores)
        seen = set(stores)
        for n in _loaded_names(load_nodes):
            if n not in seen and n not in self._skip \
                    and n != _HELPERS and not n.startswith("__dy2st_"):
                seen.add(n)
                carried.append(n)
        return carried

    def _fresh(self, kind):
        self.counter += 1
        return f"__dy2st_{kind}_{self.counter}"

    # nested function definitions keep their own control flow untouched
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node):
        self.generic_visit(node)
        try:
            _check_supported(node.body + node.orelse)
        except _Unsupported:
            return node
        stores = _assigned_names(node.body + node.orelse)
        if not stores:
            return node  # pure side-effect-free branch: nothing to thread
        carried = self._carried(stores, node.body + node.orelse)
        t_name, f_name = self._fresh("true"), self._fresh("false")
        ret = _ret_tuple(carried)
        t_fn = _make_fn(t_name, carried, list(node.body), ret)
        f_fn = _make_fn(f_name, carried,
                        list(node.orelse) if node.orelse else [ast.Pass()],
                        ret)
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in carried],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(
                    value=_name(_HELPERS, ast.Load()),
                    attr="_cvt_ifelse", ctx=ast.Load()),
                args=[node.test,
                      _name(t_name, ast.Load()),
                      _name(f_name, ast.Load()),
                      ast.Tuple(elts=[_name(n, ast.Load())
                                      for n in carried],
                                ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in carried],
                                ctx=ast.Load()),
                      ast.Constant(value=len(stores))],
                keywords=[]))
        self.changed = True
        return [_undef_guard(n) for n in carried] + [t_fn, f_fn, call]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node  # while/else: rare, unsupported
        pre = []
        if _has_break_continue(node.body):
            # reference break_continue_transformer.py: lower this-level
            # break/continue to loop-carried boolean flags, guard the
            # trailing statements, and AND `not brk` into the condition
            self.counter += 1
            brk = f"__to_static_brk_{self.counter}__"
            cont = f"__to_static_cont_{self.counter}__"
            body, _ = _lower_break_continue(node.body, brk, cont)
            node = ast.While(
                test=_lazy_and_flag(brk, node.test),
                body=[_assign_const(cont, False)] + body, orelse=[])
            # both flags seeded OUTSIDE too: the while rewrite carries
            # them as loop state from their pre-loop bindings
            pre = [_assign_const(brk, False), _assign_const(cont, False)]
            self.changed = True
            # convert the ifs the lowering produced (the first
            # generic_visit skipped them while they contained break)
            self.generic_visit(node)
        try:
            _check_supported(node.body)
        except _Unsupported:
            return pre + [node] if pre else node
        stores = _assigned_names(node.body)
        if not stores:
            return pre + [node] if pre else node
        carried = self._carried(stores, node.body + [node.test])
        c_name, b_name = self._fresh("cond"), self._fresh("body")
        c_fn = _make_fn(c_name, carried, [], ast.Return(value=node.test))
        b_fn = _make_fn(b_name, carried, list(node.body),
                        _ret_tuple(carried))
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in carried],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(
                    value=_name(_HELPERS, ast.Load()),
                    attr="_cvt_while", ctx=ast.Load()),
                args=[_name(c_name, ast.Load()),
                      _name(b_name, ast.Load()),
                      ast.Tuple(elts=[_name(n, ast.Load())
                                      for n in carried],
                                ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in carried],
                                ctx=ast.Load()),
                      ast.Constant(value=len(stores))],
                keywords=[]))
        self.changed = True
        return pre + [_undef_guard(n) for n in carried] + [c_fn, b_fn, call]

    def visit_For(self, node):
        """``for i in range(...)`` rewrites into ``_cvt_for_range``, whose
        RUNTIME dispatch keeps exact Python semantics (loop-var binding,
        empty ranges, step=0 ValueError) when every bound is a plain int
        and lowers to XLA While only when a bound is a traced Tensor
        (reference: dygraph_to_static loop_transformer + convert_range).
        Everything else (iterating lists, tensors with static leading
        dim, enumerate, zip, shadowed ``range``) is left untouched."""
        # user-level stores, captured BEFORE generic_visit: inner
        # if/while rewrites fabricate tuple-assign stores of every name
        # they carry (including read-only ones like this loop's var),
        # which would spuriously trip the rebinding bail below
        stores = _assigned_names(node.body)
        self.generic_visit(node)
        if self.range_shadowed:
            return node  # a user `range` binding: name-match is unsound
        if not _is_range_for(node):
            return node
        it = node.iter
        if _has_break_continue(node.body):
            # reference loop_transformer: a range-for with break/continue
            # lowers to the explicit while form, whose rewrite carries
            # the flags as loop state
            setup, wnode = _range_for_to_while(node)
            result = self.visit_While(wnode)
            self.changed = True
            return setup + (result if isinstance(result, list)
                            else [result])
        try:
            _check_supported(node.body)
        except _Unsupported:
            return node
        tgt = node.target.id
        if tgt in stores:
            # `for i ...: i = ...` — body rebinding of the loop var has
            # observable post-loop semantics the closure drop would lose
            return node
        # evaluate the range arguments LEFT-TO-RIGHT (python call-arg
        # order; side-effecting bounds must see each other's effects)
        arg_ns = [self._fresh("rng") for _ in it.args]
        setup = [ast.Assign(targets=[_name(n, ast.Store())], value=a)
                 for n, a in zip(arg_ns, it.args)]
        if len(arg_ns) == 1:
            start, stop, step = ast.Constant(value=0), \
                _name(arg_ns[0], ast.Load()), ast.Constant(value=1)
        elif len(arg_ns) == 2:
            start, stop, step = _name(arg_ns[0], ast.Load()), \
                _name(arg_ns[1], ast.Load()), ast.Constant(value=1)
        else:
            start, stop, step = [_name(n, ast.Load()) for n in arg_ns]
        carried = [n for n in self._carried(stores, node.body) if n != tgt]
        b_name = self._fresh("forbody")
        b_fn = _make_fn(b_name, [tgt] + carried, list(node.body),
                        _ret_tuple(carried))
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in [tgt] + carried],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Attribute(value=_name(_HELPERS, ast.Load()),
                                   attr="_cvt_for_range", ctx=ast.Load()),
                args=[start, stop, step,
                      _name(b_name, ast.Load()),
                      _name(tgt, ast.Load()),
                      ast.Tuple(elts=[_name(n, ast.Load())
                                      for n in carried], ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in carried], ctx=ast.Load()),
                      ast.Constant(value=len(stores))],
                keywords=[]))
        self.changed = True
        return (setup + [_undef_guard(n) for n in [tgt] + carried]
                + [b_fn, call])


_RET = "__to_static_ret__"  # deliberately NOT a __dy2st_ name: it must be
# visible to _assigned_names so the if-rewrite carries it
_RETF = "__to_static_retflag__"  # return-flag for returns under loops


def _count_returns(node):
    n = 0

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, nd):  # nested defs own their returns
            return

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Return(self, nd):
            nonlocal n
            n += 1

    V().visit(node)
    return n


def _hoist_early_returns(stmts):
    """Rewrite TAIL-POSITION early returns into if/else assignment form
    so the if-rewriter can convert them (reference:
    dygraph_to_static/return_transformer.py handles the general case;
    this covers the overwhelmingly common model pattern)::

        if c:              if c:
            return A   ->      __to_static_ret__ = A
        S                  else:
        return B               S
                               __to_static_ret__ = B
                           return __to_static_ret__

    Applied recursively; bails (leaves statements untouched) whenever a
    branch has non-tail returns."""
    out = list(stmts)
    for s in out:
        if isinstance(s, ast.If):
            s.body = _hoist_early_returns(s.body)
            if s.orelse:
                s.orelse = _hoist_early_returns(s.orelse)
    for i, s in enumerate(out):
        if isinstance(s, ast.If) and not s.orelse and out[i + 1:] and \
                s.body and isinstance(s.body[-1], ast.Return):
            s.orelse = _hoist_early_returns(out[i + 1:])
            out = out[:i + 1]
            break
    if out and isinstance(out[-1], ast.If):
        s = out[-1]
        if (s.orelse and s.body
                and isinstance(s.body[-1], ast.Return)
                and isinstance(s.orelse[-1], ast.Return)
                and _count_returns(s) == 2):
            for branch in (s.body, s.orelse):
                ret = branch[-1]
                branch[-1] = ast.Assign(
                    targets=[_name(_RET, ast.Store())],
                    value=ret.value if ret.value is not None
                    else ast.Constant(value=None))
            out.append(ast.Return(value=_name(_RET, ast.Load())))
    return out


def _has_return(stmts):
    """True if any Return exists in the statements (descending ifs,
    loops, try/with — NOT nested function defs)."""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            return

        visit_AsyncFunctionDef = visit_ClassDef = visit_FunctionDef

        def visit_Return(self, node):
            found[0] = True

    for s in stmts:
        V().visit(s)
    return found[0]


def _has_early_return(body):
    """A return NOT in top-level tail position (i.e. nested under
    control flow) remains after hoisting."""
    return any(not isinstance(s, ast.Return) and _has_return([s])
               for s in body)


def _lower_returns_general(body):
    """Flag-based return lowering (reference: return_transformer.py) —
    handles `return` under LOOPS, which the tail hoist cannot::

        while c:                 __to_static_retflag__ = False
            if p: return A       __to_static_ret__ = None
            S                    while _cvt_and_lazy(not RETF, c):
        return B                     if p: RETF = True; RET = A
                                     if _cvt_not(RETF): S
                                 if _cvt_not(RETF): RET = B
                                 return RET

    Raises _Unsupported (caller falls back to trace) for returns under
    try/with or non-range fors."""

    def process(stmts):
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                out.append(_assign_const(_RETF, True))
                out.append(ast.Assign(
                    targets=[_name(_RET, ast.Store())],
                    value=s.value if s.value is not None
                    else ast.Constant(value=None)))
                return out, True
            if isinstance(s, (ast.Try, ast.With)) and _has_return([s]):
                raise _Unsupported("return under try/with")
            if isinstance(s, ast.If):
                b, fb = process(s.body)
                o, fo = process(s.orelse)
                if fb or fo:
                    out.append(ast.If(test=s.test, body=b or [ast.Pass()],
                                      orelse=o))
                    out.extend(_guard_rest(stmts[i + 1:]))
                    return out, True
                out.append(s)
                continue
            if isinstance(s, ast.While) and _has_return(
                    s.body + s.orelse):
                if s.orelse:
                    raise _Unsupported("return in while-else")
                nb, _ = process(s.body)
                out.append(ast.While(
                    test=_lazy_retf_and(s.test), body=nb, orelse=[]))
                out.extend(_guard_rest(stmts[i + 1:]))
                return out, True
            if isinstance(s, ast.For) and _has_return(s.body + s.orelse):
                setup, wnode = _range_for_to_while(s)
                nb, _ = process(wnode.body)
                out.extend(setup)
                out.append(ast.While(
                    test=_lazy_retf_and(wnode.test), body=nb, orelse=[]))
                out.extend(_guard_rest(stmts[i + 1:]))
                return out, True
            out.append(s)
        return out, False

    def _guard_rest(rest_stmts):
        rest, _ = process(rest_stmts)
        if not rest:
            return []
        return [ast.If(test=_helper_call(
            "_cvt_not", [_name(_RETF, ast.Load())]),
            body=rest, orelse=[])]

    def _lazy_retf_and(test):
        return _lazy_and_flag(_RETF, test)

    new, changed = process(body)
    if not changed:
        return body
    return ([_assign_const(_RETF, False),
             ast.Assign(targets=[_name(_RET, ast.Store())],
                        value=ast.Constant(value=None))]
            + new + [ast.Return(value=_name(_RET, ast.Load()))])


def convert_function(fn):
    """Return a control-flow-converted clone of ``fn``, or ``fn`` itself
    when the pass does not apply (no rewritable statements, no source,
    free variables, @not_to_static, ...)."""
    if getattr(fn, "_not_to_static", False):
        return fn
    if inspect.ismethod(fn):
        conv = convert_function(fn.__func__)
        return fn if conv is fn.__func__ else conv.__get__(fn.__self__)
    raw = inspect.unwrap(fn)
    freevars, freevals = (), ()
    if getattr(raw, "__closure__", None):
        # closures: re-wrap the converted def in a factory taking the
        # free variables as parameters — the cells are SNAPSHOT at
        # conversion (the trace target is rebuilt per StaticFunction, so
        # this matches when the closure binds layers/optimizers, the
        # overwhelmingly common to_static pattern)
        try:
            freevals = tuple(c.cell_contents for c in raw.__closure__)
        except ValueError:  # empty cell (self-referential def)
            return fn
        freevars = raw.__code__.co_freevars
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []
    rw = _Rewriter(global_names=raw.__globals__.keys(),
                   local_names=raw.__code__.co_varnames,
                   free_names=raw.__code__.co_freevars,
                   # a module-global, local, or closed-over `range`
                   # binding makes the name-based for-range match unsound
                   range_shadowed=("range" in raw.__globals__
                                   or "range" in raw.__code__.co_varnames
                                   or "range" in raw.__code__.co_freevars))
    # visit the body statements, not fdef itself — visit_FunctionDef
    # guards NESTED defs only
    fdef.body = _hoist_early_returns(fdef.body)
    if _has_early_return(fdef.body):
        # returns under loops (or if-shapes the tail hoist can't touch):
        # flag-based lowering; trace fallback on unsupported shapes
        try:
            fdef.body = _lower_returns_general(fdef.body)
        except _Unsupported:
            pass
    new_body = []
    for s in fdef.body:
        r = rw.visit(s)
        if isinstance(r, list):
            new_body.extend(r)
        elif r is not None:
            new_body.append(r)
    fdef.body = new_body
    if not rw.changed:
        return fn
    if freevars:
        factory = _make_fn(
            "__dy2st_factory__", list(freevars), [fdef],
            ast.Return(value=_name(fdef.name, ast.Load())))
        tree = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<dy2static {raw.__name__}>", "exec")
    except (SyntaxError, ValueError):
        return fn
    import sys

    namespace = dict(raw.__globals__)
    namespace[_HELPERS] = sys.modules[__name__]
    exec(code, namespace)
    if freevars:
        converted = namespace["__dy2st_factory__"](*freevals)
    else:
        converted = namespace[fdef.name]
    converted.__defaults__ = raw.__defaults__
    converted.__kwdefaults__ = raw.__kwdefaults__
    converted._dy2static_converted = True
    return converted
