# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.jit analog: compile eager code to one XLA executable.

Replaces the reference dy2static stack
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py ProgramTranslator, ConcreteProgram input-spec cache,
partial_program.py) the TPU-native way: instead of AST-rewriting Python into
a ProgramDesc, the function is traced with JAX abstract values straight to
StableHLO and compiled by XLA.

What the trace captures as *program state* (inputs AND outputs):
  - every Parameter of the layers involved (so weight updates inside the
    traced fn — optimizer.step() — become functional outputs)
  - every Layer buffer (BN running stats etc.)
  - optimizer accumulator slots + device step counter
  - the RNG key (dropout draws fold_in from a per-call key input)
  - each optimizer's learning rate (a dynamic scalar input, so LR schedules
    don't retrace)

The eager tape keeps working inside the trace (jax.vjp over tracers), so a
whole train_step — forward, loss.backward(), optimizer.step() — compiles to
one fused XLA program.  Data-dependent Python control flow must use
paddle_tpu.jit.cond/while_loop/scan (→ XLA control flow), matching the
reference's static control-flow ops (fluid/layers/control_flow.py While:1024).
"""
from __future__ import annotations

import contextlib
import functools
import itertools
import types
from typing import Any, Dict, List

import jax
import jax.export  # noqa: F401 — jax.export is lazy; attribute access alone fails
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtype import to_np
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops import random as rnd

__all__ = ["to_static", "not_to_static", "InputSpec", "save", "load", "cond",
           "while_loop", "scan", "StaticFunction"]


class InputSpec:
    """paddle.static.InputSpec analog."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _is_arrayish(v):
    return isinstance(v, (jnp.ndarray, np.ndarray)) or (
        hasattr(v, "aval") and hasattr(v, "dtype"))


@functools.lru_cache(maxsize=4096)
def _code_global_names(code) -> tuple:
    """Names a code object (incl. NESTED code objects) reads via
    LOAD_GLOBAL/LOAD_NAME.  A layer referenced only inside a local
    helper (`def body(i, acc): return i+1, acc+lin(x)`) is just as
    load-bearing as one named at the top level — missing it silently
    discards its weight updates AND leaks the trace tracer into the
    live param.  LOAD_GLOBAL only (co_names also holds attribute names,
    which must not pull in unrelated same-named globals).  Memoized per
    code object: callers run per jit.cond/while_loop/scan invocation."""
    import dis

    names, codes = [], [code]
    while codes:
        c = codes.pop()
        for ins in dis.get_instructions(c):
            if ins.opname in ("LOAD_GLOBAL", "LOAD_NAME"):
                names.append(ins.argval)
        codes.extend(k for k in c.co_consts
                     if isinstance(k, types.CodeType))
    seen, out = set(), []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return tuple(out)


def _referenced_objects(obj):
    """Objects a function can reach: bound self, closure cells, and the
    module globals its code names.  This is how the trace discovers which
    Layers/Optimizers hold state (the reference discovers them through
    ProgramTranslator's parameter recorder)."""
    out = []
    bound_self = getattr(obj, "__self__", None)
    if bound_self is not None:
        out.append(bound_self)
    fn = getattr(obj, "__func__", obj)
    code = getattr(fn, "__code__", None)
    if code is not None:
        g = getattr(fn, "__globals__", {})
        for name in _code_global_names(code):
            if name in g:
                out.append(g[name])
        for cell in (fn.__closure__ or ()):
            try:
                out.append(cell.cell_contents)
            except ValueError:
                pass
    for d in (getattr(fn, "__defaults__", None) or ()):
        out.append(d)
    return out


def _flatten_candidates(objs):
    flat = []
    for v in objs:
        flat.append(v)
        if isinstance(v, (list, tuple)):
            flat.extend(v)
        elif isinstance(v, dict):
            flat.extend(v.values())
    return flat


def _find_layers(obj, seen=None) -> List[Layer]:
    seen = seen if seen is not None else set()
    out = []
    if isinstance(obj, Layer):
        if id(obj) not in seen:
            seen.add(id(obj))
            out.append(obj)
        return out
    for v in _flatten_candidates(_referenced_objects(obj)):
        if isinstance(v, Layer) and id(v) not in seen:
            seen.add(id(v))
            out.append(v)
    return out


def _find_optimizers(obj) -> list:
    from ..optimizer.optimizer import Optimizer

    out = []
    seen = set()
    for v in _flatten_candidates(_referenced_objects(obj)):
        # meta-optimizer wrappers (GradientMerge/LocalSGD) hold the real
        # Optimizer as ._inner — unwrap so its state threads through
        hops = 0
        while not isinstance(v, Optimizer) and hops < 4 and \
                getattr(v, "_inner", None) is not None:
            v = v._inner
            hops += 1
        if isinstance(v, Optimizer) and id(v) not in seen:
            seen.add(id(v))
            out.append(v)
    return out


class _State:
    """Handles to every mutable array a trace must thread through."""

    def __init__(self, layers, optimizers):
        self.params: List[Tensor] = []
        self.buffers: List[Tensor] = []
        seen = set()
        for layer in layers:
            for _, p in layer.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    self.params.append(p)
            for _, b in layer.named_buffers():
                if id(b) not in seen:
                    seen.add(id(b))
                    self.buffers.append(b)
        self.optimizers = list(optimizers)
        # BARE tensors handed straight to an optimizer (no Layer) are
        # state too: reference scripts train plain
        # paddle.to_tensor(stop_gradient=False) params; without this,
        # opt.step() under trace writes a tracer into the live value and
        # the update is silently lost
        for opt in self.optimizers:
            for p in (getattr(opt, "_parameter_list", None) or ()):
                # parameter-GROUP dicts ({'params': [...], 'lr': ...})
                # hold bare tensors too (optimizer.py _static_minimize
                # flattens them the same way)
                entries = (p.get("params", []) if isinstance(p, dict)
                           else [p])
                for q in entries:
                    if isinstance(q, Tensor) and id(q) not in seen:
                        seen.add(id(q))
                        self.params.append(q)

    def opt_slots(self):
        slots = []
        for opt in self.optimizers:
            for name in sorted(opt._accumulators):
                store = opt._accumulators[name]
                for pid in sorted(store):
                    slots.append((store, pid))
            for key in sorted(opt._global_state):
                slots.append((opt._global_state, key))
        return slots

    def read(self):
        return ([p._value for p in self.params]
                + [b._value for b in self.buffers]
                + [store[k] for store, k in self.opt_slots()])

    def write(self, vals, slots=None):
        n_p, n_b = len(self.params), len(self.buffers)
        for p, v in zip(self.params, vals[:n_p]):
            p._value = v
            p.grad = None
            p._grad_node = None
        for b, v in zip(self.buffers, vals[n_p:n_p + n_b]):
            b._value = v
        slots = slots if slots is not None else self.opt_slots()
        for (store, k), v in zip(slots, vals[n_p + n_b:]):
            store[k] = v

    def signature(self):
        return (len(self.params), len(self.buffers),
                tuple((id(s), k) for s, k in self.opt_slots()))


def _spec_key(flat_static, treedef, dyn_leaves):
    dyn = tuple((tuple(v.shape), str(v.dtype)) for v in dyn_leaves)
    stat = tuple(
        v if isinstance(v, (int, float, bool, str, bytes, type(None)))
        else repr(v) for v in flat_static)
    return (dyn, stat, str(treedef))


class StaticFunction:
    """Compiled callable with an input-spec cache (the ConcreteProgram cache
    analog, reference: program_translator.py)."""

    def __init__(self, fn, input_spec=None, loop_max_trips=None, **unused):
        self._fn = fn
        self._traced_fn = None  # dy2static-converted clone, built lazily
        self._input_spec = input_spec
        # bound for tensor-condition python loops: lowers them to the
        # differentiable bounded while (scan-of-cond) so reference-style
        # training scripts with data-dependent loops work end to end
        self._loop_max_trips = loop_max_trips
        self._cache: Dict[Any, Any] = {}
        self._bound_cache: Dict[int, "StaticFunction"] = {}
        self._layers = None
        self._optimizers = None
        self._mode_layers = None
        self._state = None
        self._state_version = -1
        functools.update_wrapper(self, fn, updated=[])

    def _trace_target(self):
        """The function the tracer compiles: the AST-converted clone when
        the dy2static pass applies (data-dependent if/while ->
        jit.cond/while_loop, reference program_translator semantics), the
        original otherwise.  ProgramTranslator.enable(False) bypasses
        this entirely — the ORIGINAL runs eagerly."""
        if self._traced_fn is None:
            from . import dy2static

            try:
                self._traced_fn = dy2static.convert_function(self._fn)
            except Exception:  # noqa: BLE001 — the pass must never break
                self._traced_fn = self._fn
        return self._traced_fn

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        bound = self._bound_cache.get(id(instance))
        if bound is None:
            bound = StaticFunction(self._fn.__get__(instance, owner),
                                   self._input_spec,
                                   loop_max_trips=self._loop_max_trips)
            self._bound_cache[id(instance)] = bound
        return bound

    def _discover(self, args, kwargs):
        layers = _find_layers(self._fn)
        opts = _find_optimizers(self._fn)
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, Layer):
                for l in _find_layers(a):
                    if all(l is not x for x in layers):
                        layers.append(l)
        self._layers = layers
        self._optimizers = opts

    def __call__(self, *args, **kwargs):
        # global dy2static switch (ProgramTranslator.enable(False) runs
        # the original python function eagerly, reference semantics)
        if not ProgramTranslator._enabled:
            return self._fn(*args, **kwargs)
        if self._layers is None:
            self._discover(args, kwargs)
        if self._state is None or \
                self._state_version != Layer._structure_version:
            # param/buffer handle lists are stable until SOME layer
            # mutates structurally (cheap global int compare); the
            # VALUES are read through the handles each call (read()),
            # and opt slots are re-walked in signature()/opt_slots()
            self._state = _State(self._layers, self._optimizers)
            self._state_version = Layer._structure_version
            self._mode_layers = None  # sublayer list may have changed
        state = self._state

        raw_tree = jax.tree_util.tree_map(
            lambda x: x._value if isinstance(x, Tensor) else x, (args, kwargs),
            is_leaf=lambda x: isinstance(x, Tensor))
        flat, treedef = jax.tree_util.tree_flatten(raw_tree)
        dyn_idx = [i for i, v in enumerate(flat) if _is_arrayish(v)]
        dyn_vals = [flat[i] for i in dyn_idx]
        static_flat = [None if i in dyn_idx else v for i, v in enumerate(flat)]

        # train/eval mode is part of the program (dropout identity, BN
        # statistics source), not a traced value — a .eval() flip after
        # compilation must select/build a different executable, or the
        # train-mode program keeps running silently.  The sublayer LIST
        # is cached (stable per discovery); the flags are read per call.
        if self._mode_layers is None:
            self._mode_layers = [sl for layer in self._layers
                                 for sl in layer.sublayers(
                                     include_self=True)]
        mode_key = tuple(sl.training for sl in self._mode_layers)
        # the mesh is part of the program: a distributed.MeshExecutor
        # bound here (executor.install) means the entry jits with
        # explicit per-invar shardings, and a mesh change must
        # select/build a different executable
        mesh_exec = getattr(self, "_mesh_executor", None)
        key = (_spec_key(static_flat, treedef, dyn_vals), state.signature(),
               mode_key,
               None if mesh_exec is None else mesh_exec.cache_token())
        entry = self._cache.get(key)
        if entry is None:
            in_sh = (None if mesh_exec is None
                     else mesh_exec.train_in_shardings(state, dyn_vals))
            entry = _CompiledEntry(self._trace_target(), state, treedef,
                                   static_flat, tuple(dyn_idx),
                                   in_shardings=in_sh,
                                   mesh_exec=mesh_exec)
            self._cache[key] = entry

        # host numpy (not device jnp): in a multi-controller runtime
        # (jax.distributed.initialize) a committed single-device array is
        # not a valid jit input over a multi-process mesh, while numpy
        # values are treated as replicated (same on every process)
        lrs = np.asarray([opt.get_lr() for opt in state.optimizers],
                         np.float32)
        # host-derived key data (counter XOR seed): no traced op per call
        # — and identically replicated across multi-controller processes
        rng_key = rnd.default_generator().next_key_data()
        from .dy2static import _LOOP_MAX_TRIPS

        _LOOP_MAX_TRIPS.append(self._loop_max_trips)
        try:
            if entry._param_mutated is None:
                entry.probe_trace(state, dyn_vals, lrs, rng_key)
            if entry._param_mutated is False and \
                    getattr(entry, "_out_all_arrays", False) and \
                    dispatch.is_grad_enabled():
                orig_flat = jax.tree_util.tree_flatten(
                    (args, kwargs),
                    is_leaf=lambda x: isinstance(x, Tensor))[0]
                dyn_objs = [orig_flat[i] for i in dyn_idx]
                if any(not p.stop_gradient for p in state.params) or any(
                        isinstance(o, Tensor) and not o.stop_gradient
                        for o in dyn_objs):
                    # forward-only wrap under grad recording: the
                    # reference's canonical `@to_static` ON THE MODEL
                    # with backward outside — the compiled call must be
                    # externally differentiable
                    return entry.run_diff(state, dyn_objs, dyn_vals,
                                          lrs, rng_key)
            return entry.run(state, dyn_vals, lrs, rng_key)
        finally:
            _LOOP_MAX_TRIPS.pop()

    def trace_jaxpr(self, *args, **kwargs):
        """Abstractly trace ONE call and return ``(closed_jaxpr,
        donated_mask)`` for static analysis (paddle_tpu.analysis.xray).

        Mirrors ``__call__``'s plumbing — state discovery, Tensor
        flattening, dy2static, loop bounds — but hands the entry's
        ``jax_fn`` to ``jax.make_jaxpr`` instead of executing it.  The
        flattened invars are ``state_vals ++ dyn_vals ++ lrs ++ rng_key``
        and the real call path jits with ``donate_argnums=(0,)``, so the
        mask marks exactly the state leaves as donated.  Cleanup follows
        ``probe_trace``: optimizer slots materialized under the abstract
        trace hold tracers and are deleted; live params/buffers are
        restored by ``jax_fn``'s own finally.  The python body runs once
        under tracing, so user python side effects (step counters) fire —
        same caveat as any extra trace.
        """
        if self._layers is None:
            self._discover(args, kwargs)
        if self._state is None or \
                self._state_version != Layer._structure_version:
            self._state = _State(self._layers, self._optimizers)
            self._state_version = Layer._structure_version
            self._mode_layers = None
        state = self._state

        raw_tree = jax.tree_util.tree_map(
            lambda x: x._value if isinstance(x, Tensor) else x,
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        flat, treedef = jax.tree_util.tree_flatten(raw_tree)
        dyn_idx = [i for i, v in enumerate(flat) if _is_arrayish(v)]
        dyn_vals = [flat[i] for i in dyn_idx]
        static_flat = [None if i in dyn_idx else v
                       for i, v in enumerate(flat)]
        # a fresh entry, NOT cached: this trace never lowers/compiles,
        # and a real call must still get its own trace-exactly-once entry
        entry = _CompiledEntry(self._trace_target(), state, treedef,
                               static_flat, tuple(dyn_idx))
        entry._live_state = state
        state_vals = state.read()
        lrs = np.asarray([opt.get_lr() for opt in state.optimizers],
                         np.float32)
        rng_key = rnd.default_generator().next_key_data()
        from .dy2static import _LOOP_MAX_TRIPS

        _LOOP_MAX_TRIPS.append(self._loop_max_trips)
        pre = set(entry._pre_slot_ids)
        try:
            closed = jax.make_jaxpr(entry._jax_fn)(
                state_vals, list(dyn_vals), lrs, rng_key)
        finally:
            _LOOP_MAX_TRIPS.pop()
            for s, k in list(state.opt_slots()):
                if (id(s), k) not in pre:
                    del s[k]
        n_state = len(state_vals)
        n_in = len(closed.jaxpr.invars)
        donated = tuple(i < min(n_state, n_in) for i in range(n_in))
        return closed, donated

    # ----- parity helpers
    @property
    def code(self):
        import inspect

        return inspect.getsource(self._fn)

    def rollback(self):
        return self._fn


class _CompiledEntry:
    def __init__(self, fn, state_example, treedef, static_flat, dyn_idx,
                 in_shardings=None, mesh_exec=None):
        self._fn = fn
        self._treedef = treedef
        self._static_flat = static_flat
        self._dyn_idx = dyn_idx
        self._pre_slot_ids = [(id(s), k) for s, k in state_example.opt_slots()]
        self._new_slot_handles = []  # [(store, key)] discovered at trace time
        self._out_template = None
        # None until the first trace; False = the program leaves params
        # untouched (forward-only wrap) so external backward must work
        self._param_mutated = None
        self._nodonate = None
        self._diff_impl = None
        self._bwd_exec = None
        self._lowered = None
        self._compiled = None

        entry = self

        def jax_fn(state_vals, dyn_vals, lrs, rng_key):
            state = entry._live_state
            orig_vals = state.read()
            pre_slots = state.opt_slots()
            state.write(state_vals, slots=pre_slots)
            counter = itertools.count()

            def key_provider():
                return jax.random.fold_in(rng_key, next(counter))

            prev_provider = rnd.set_trace_key_provider(key_provider)
            prev_lrs = [opt._learning_rate for opt in state.optimizers]
            for i, opt in enumerate(state.optimizers):
                opt._learning_rate = _TracedLR(lrs[i])
            try:
                flat2 = list(entry._static_flat)
                for pos, v in zip(entry._dyn_idx, dyn_vals):
                    flat2[pos] = Tensor(v, stop_gradient=True)
                call_args, call_kwargs = jax.tree_util.tree_unflatten(
                    entry._treedef, flat2)
                with dispatch.static_trace_guard():
                    out = entry._fn(*call_args, **call_kwargs)

                post_slots = state.opt_slots()
                pre_ids = set(entry._pre_slot_ids)
                known_vals = [s[k] for s, k in post_slots
                              if (id(s), k) in pre_ids]
                new_handles = [(s, k) for s, k in post_slots
                               if (id(s), k) not in pre_ids]
                new_vals = [s[k] for s, k in new_handles]
                entry._new_slot_handles = new_handles
                n_pb = len(state.params) + len(state.buffers)
                cur = state.read()
                new_state = cur[:n_pb] + known_vals + new_vals
                if mesh_exec is not None:
                    # pin the state OUTPUTS to the planned layout: XLA's
                    # sharding propagation-to-output is otherwise free to
                    # reshard them (observed: replicated norm weights
                    # coming back fsdp-sharded), and the next call's
                    # committed args would then mismatch in_shardings
                    known_handles = [(s, k) for s, k in post_slots
                                     if (id(s), k) in pre_ids]
                    new_state = mesh_exec.constrain_state_outputs(
                        state, new_state, known_handles + new_handles)
                # identity check on tracers: a param the program never
                # touched passes through as the SAME tracer object —
                # learned here so __call__ can route forward-only wraps
                # through the externally-differentiable path
                n_p = len(state.params)
                entry._param_mutated = any(
                    c is not s for c, s in zip(cur[:n_p], state_vals[:n_p]))

                out_raw = jax.tree_util.tree_map(
                    lambda x: x._value if isinstance(x, Tensor) else x, out,
                    is_leaf=lambda x: isinstance(x, Tensor))
                entry._out_template = jax.tree_util.tree_structure(
                    out_raw, is_leaf=lambda x: x is None)
                entry._out_all_arrays = all(
                    _is_arrayish(leaf) or hasattr(leaf, "aval")
                    for leaf in jax.tree_util.tree_flatten(out_raw)[0])
            finally:
                rnd.set_trace_key_provider(prev_provider)
                for opt, prev in zip(state.optimizers, prev_lrs):
                    opt._learning_rate = prev
                # restore concrete state so tracers never leak into live objs
                state.write(orig_vals, slots=pre_slots)
            return out_raw, new_state

        self._jax_fn = jax_fn
        if in_shardings is None:
            self._jitted = jax.jit(jax_fn, donate_argnums=(0,))
        else:
            # GSPMD execution (distributed.MeshExecutor): committed
            # per-invar layouts make this one multi-device program, and
            # donation pins the state outputs to the same layouts
            self._jitted = jax.jit(jax_fn, donate_argnums=(0,),
                                   in_shardings=in_shardings)

    def run(self, state, dyn_vals, lrs, rng_key):
        self._live_state = state
        n_known = (len(state.params) + len(state.buffers)
                   + len(self._pre_slot_ids))
        if self._compiled is None and self._lowered is not None:
            try:
                self._compiled = self._lowered.compile()
            except Exception as e:  # noqa: BLE001
                import warnings

                # the plain-jit fallback RE-TRACES the python body — a
                # documented trace-exactly-once violation (user python
                # side effects like step counters run twice), so say so
                # instead of silently desyncing (ADVICE r4)
                warnings.warn(
                    f"compiled-call build failed ({type(e).__name__}: "
                    f"{e}); falling back to plain jit, which re-traces "
                    "the function body (python side effects run again)")
                self._lowered = None  # fall back to the plain jit call
        if self._compiled is not None:
            out_raw, new_state = self._compiled(
                state.read(), list(dyn_vals), lrs, rng_key)
        else:
            out_raw, new_state = self._jitted(state.read(), dyn_vals, lrs,
                                              rng_key)
        pre_slots = [(s, k) for s, k in state.opt_slots()
                     if (id(s), k) in set(self._pre_slot_ids)]
        state.write(new_state[:n_known], slots=pre_slots)
        for (store, k), v in zip(self._new_slot_handles, new_state[n_known:]):
            store[k] = v
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if _is_arrayish(v) else v, out_raw)

    def probe_trace(self, state, dyn_vals, lrs, rng_key):
        """Abstractly trace once (no execution) so _param_mutated and the
        output template are known before choosing an execution path."""
        self._live_state = state
        pre = set(self._pre_slot_ids)
        try:
            # the SAME lowering later compiles into the standard path's
            # executable — the python body must trace exactly once per
            # entry (user code may have python-side effects, e.g.
            # gradient-merge step counters; a second trace desyncs them)
            self._lowered = self._jitted.lower(
                state.read(), list(dyn_vals), lrs, rng_key)
        except Exception:  # noqa: BLE001 — let the real call surface it
            self._param_mutated = True
        finally:
            # optimizer slots materialized during the ABSTRACT trace hold
            # tracers (nothing executed, so nothing wrote real values) —
            # delete the VALUES; _new_slot_handles is kept so run()'s
            # writeback recreates the entries from the compiled program's
            # concrete outputs
            for s, k in list(state.opt_slots()):
                if (id(s), k) not in pre:
                    del s[k]

    def _ensure_diff(self, state):
        if self._diff_impl is not None:
            return
        _register_diff_dispatch()

        jax_fn = self._jax_fn
        self._n_params = len(state.params)
        self._nodonate = jax.jit(jax_fn)

        def _flat_out(sv, dv, lrs, key):
            out_raw, _ns = jax_fn(sv, dv, lrs, key)
            return tuple(jax.tree_util.tree_flatten(out_raw)[0])

        @jax.jit
        def _bwd(pv, rest, dv, lrs, key, ct):
            # recompute-based vjp (one extra forward at backward time);
            # jitted, so the linearization compiles ONCE per signature
            _, vjp = jax.vjp(
                lambda p, d: _flat_out(list(p) + list(rest), list(d),
                                       lrs, key), tuple(pv), tuple(dv))
            return vjp(tuple(ct))

        self._bwd_exec = _bwd
        self._diff_impl = _to_static_diff_impl

    def run_diff(self, state, dyn_objs, dyn_vals, lrs, rng_key):
        """Externally-differentiable execution for programs that leave
        params untouched (the reference's canonical `@to_static` on the
        MODEL, backward outside).  The compiled forward rides the tape
        as ONE op; grads reach params and differentiable inputs via a
        cached jitted recompute-vjp.  Buffer/slot mutations (BN stats)
        still write back."""
        from ..core.dispatch import apply

        self._live_state = state
        self._ensure_diff(state)
        dyn_wrapped = [
            d if isinstance(d, Tensor) else Tensor(jnp.asarray(v),
                                                   stop_gradient=True)
            for d, v in zip(dyn_objs, dyn_vals)]
        lr_t = Tensor(jnp.asarray(lrs), stop_gradient=True)
        key_t = Tensor(jnp.asarray(rng_key), stop_gradient=True)
        _DIFF_ENTRY_STACK.append(self)
        try:
            out = apply("to_static_call", self._diff_impl,
                        list(state.params), dyn_wrapped, lr_t, key_t)
        finally:
            _DIFF_ENTRY_STACK.pop()
        out = out if isinstance(out, tuple) else (out,)
        new_state = self._diff_new_state
        n_known = (len(state.params) + len(state.buffers)
                   + len(self._pre_slot_ids))
        pre_slots = [(s, k) for s, k in state.opt_slots()
                     if (id(s), k) in set(self._pre_slot_ids)]
        # params are untouched by definition of this path: write back
        # buffers + slots only, keeping param objects bound to the tape.
        # When apply bypassed the rule (AMP cast, no-grad raw path inside
        # a vjp trace), new_state leaves may be tracers of a trace we
        # don't own — skip those writebacks rather than poison live state.
        n_p = len(state.params)
        buf_and_slots = new_state[n_p:n_known]

        def _safe(old, v):
            return old if isinstance(v, jax.core.Tracer) and not isinstance(
                old, jax.core.Tracer) else v

        for b, v in zip(state.buffers, buf_and_slots[:len(state.buffers)]):
            b._value = _safe(b._value, v)
        for (s, k), v in zip(pre_slots, buf_and_slots[len(state.buffers):]):
            s[k] = _safe(s[k], v)
        for (store, k), v in zip(self._new_slot_handles,
                                 new_state[n_known:]):
            if not isinstance(v, jax.core.Tracer):
                store[k] = v
        return jax.tree_util.tree_unflatten(self._diff_out_td, list(out))


# ---- shared dispatch for externally-differentiable compiled calls.
# ONE registry entry total (registered lazily); the active _CompiledEntry
# rides a stack around the apply() call, so entries are never pinned by
# the module-global registry and the rule scan stays O(1).
_DIFF_ENTRY_STACK: List["_CompiledEntry"] = []
_DIFF_REGISTERED = []


def _to_static_diff_impl(params, dyn, lrs, key):
    """Fallback executable for apply() paths that bypass the eager-vjp
    rule (AMP-cast dispatch, raw no-grad calls, vjp re-trace): runs the
    non-donating compiled program directly.  Under an outer jax trace it
    simply inlines."""
    entry = _DIFF_ENTRY_STACK[-1]
    n_p = entry._n_params
    sv = entry._live_state.read()
    out_raw, new_state = entry._nodonate(
        list(params) + sv[n_p:], list(dyn), lrs, key)
    entry._diff_new_state = new_state
    flat, td = jax.tree_util.tree_flatten(out_raw)
    entry._diff_out_td = td
    return tuple(flat)


def _to_static_diff_rule(vals, attrs):
    # vals: flattened [*params, *dyn, lrs_arr, key_arr] raw values
    entry = _DIFF_ENTRY_STACK[-1]
    n_p = entry._n_params
    nd = len(vals) - n_p - 2
    pv, dv = vals[:n_p], vals[n_p:n_p + nd]
    lrs_v, key_v = vals[-2], vals[-1]
    sv = entry._live_state.read()
    out_raw, new_state = entry._nodonate(
        list(pv) + sv[n_p:], list(dv), lrs_v, key_v)
    entry._diff_new_state = new_state
    flat, td = jax.tree_util.tree_flatten(out_raw)
    entry._diff_out_td = td
    rest = tuple(sv[n_p:])
    bwd = entry._bwd_exec

    def vjp_all(ct):
        ct_t = tuple(ct) if isinstance(ct, (tuple, list)) else (ct,)
        gp, gd = bwd(tuple(pv), rest, tuple(dv), lrs_v, key_v, ct_t)
        return tuple(gp) + tuple(gd) + (None, None)

    return tuple(flat), vjp_all


def _register_diff_dispatch():
    if not _DIFF_REGISTERED:
        from ..core import dispatch as _d

        _d.register_eager_vjp("to_static_call", _to_static_diff_impl,
                              _to_static_diff_rule, allow_containers=True)
        _DIFF_REGISTERED.append(True)


class _TracedLR(float):
    """float subclass carrying the traced LR; arithmetic with arrays uses the
    traced value (optimizer rules receive it as a jit argument)."""

    def __new__(cls, traced):
        obj = super().__new__(cls, float("nan"))
        obj.traced = traced
        return obj


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, loop_max_trips=None, **kwargs):
    """Decorator/wrapper compiling a function or Layer to XLA.

    ``loop_max_trips=N`` bounds tensor-condition python loops (dy2static
    while / for-range over a Tensor) so they lower to the differentiable
    bounded while (scan-of-cond) instead of forward-only XLA While —
    training scripts with data-dependent loops then work unchanged."""
    if isinstance(function, Layer):
        function.forward = StaticFunction(function.forward, input_spec,
                                          loop_max_trips=loop_max_trips)
        return function
    if function is not None:
        return StaticFunction(function, input_spec,
                              loop_max_trips=loop_max_trips)

    def deco(fn):
        return to_static(fn, input_spec, build_strategy, backend,
                         loop_max_trips=loop_max_trips, **kwargs)
    return deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    """Mark every public function of the given module(s) as not-to-static
    (reference: jit/api.py ignore_module tells the AST transcriber to skip
    third-party modules).  Here trace-based to_static executes Python
    directly, so "ignored" means: functions keep their eager semantics and
    are never rewritten — implemented by tagging them like @not_to_static
    so the dy2static AST pass and trace machinery leave them alone."""
    import types

    if not isinstance(modules, (list, tuple)):
        modules = [modules]
    for mod in modules:
        for attr in dir(mod):
            fn = getattr(mod, attr, None)
            if isinstance(fn, types.FunctionType) and \
                    getattr(fn, "__module__", None) == getattr(
                        mod, "__name__", None):
                try:
                    fn._not_to_static = True
                except (AttributeError, TypeError):
                    pass


# ------------------------------------------------------------- control flow
def _as_raw(x):
    return x._value if isinstance(x, Tensor) else x


def _wrap_tree(t):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if _is_arrayish(v) else v, t)


def _unwrap_tree(t):
    return jax.tree_util.tree_map(
        _as_raw, t, is_leaf=lambda x: isinstance(x, Tensor))


def _collect_captured_params(fn, seen=None, depth=0):
    """Differentiable Tensors reachable from fn's closure — recursing
    into nested function cells, Layers (their parameters), and small
    containers.  These must ride as explicit tape operands or backward
    through a dispatched cond/scan silently misses them (the classic
    RNN-cell-closing-over-weights pattern)."""
    if seen is None:
        seen = {}
    if fn is None or depth > 4:
        return seen
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            _collect_from_value(cell.cell_contents, seen, depth)
        except ValueError:  # empty cell
            continue
    # module-global tensors/layers the code references by NAME (a
    # module-level ``lin = nn.Linear(...)`` used inside the body is just
    # as load-bearing as a closure cell); _code_global_names scans
    # LOAD_GLOBALs of the body's (possibly nested) code objects.
    code = getattr(fn, "__code__", None)
    glob = getattr(fn, "__globals__", None)
    if code is not None and glob is not None:
        for nm in _code_global_names(code):
            v = glob.get(nm)
            if isinstance(v, (Tensor, Layer)):
                _collect_from_value(v, seen, depth)
    return seen


def _collect_from_value(v, seen, depth):
    if isinstance(v, Tensor):
        if not v.stop_gradient and id(v) not in seen:
            seen[id(v)] = v
    elif isinstance(v, Layer):
        for p in v.parameters():
            if not p.stop_gradient and id(p) not in seen:
                seen[id(p)] = p
    elif isinstance(v, (list, tuple)) and len(v) <= 64:
        for e in v:
            _collect_from_value(e, seen, depth)
    elif isinstance(v, dict) and len(v) <= 64:
        for e in v.values():
            _collect_from_value(e, seen, depth)
    elif callable(v) and (getattr(v, "__closure__", None)
                          or getattr(v, "__code__", None)):
        _collect_captured_params(v, seen, depth + 1)


@contextlib.contextmanager
def _substituted(captured, vals):
    """Temporarily rebind each captured Tensor's ``_value`` (functional
    substitution during a control-flow trace) with no grad recording —
    the dispatched outer op owns differentiation.  ONE implementation
    shared by cond/scan/while so the substitution protocol cannot
    drift between them."""
    from ..core.dispatch import no_grad_ctx

    saved = [t._value for t in captured]
    try:
        for t, v in zip(captured, vals):
            t._value = v
        with no_grad_ctx():
            yield
    finally:
        for t, s in zip(captured, saved):
            t._value = s


def _tape_cond(pred, true_fn, false_fn, operands, op_name="jit_cond"):
    """Dispatch ONE tape op whose forward is lax.cond — jax-
    differentiable, so backward reaches both the explicit operands and
    any differentiable tensors the branches capture by closure (those
    are auto-promoted to operands and functionally substituted during
    the branch trace).  Shared by jit.cond and the dy2static if-rewrite."""
    from ..core.dispatch import apply

    captured = list({**_collect_captured_params(true_fn),
                     **_collect_captured_params(false_fn)}.values())
    out_td = []

    def _fn(p, ops, cap_vals):
        def run(branch):
            def inner(packed):
                raw_ops, caps = packed
                with _substituted(captured, caps):
                    res = _unwrap_tree(branch(*_wrap_tree(raw_ops)))
                flat, td = jax.tree_util.tree_flatten(res)
                if not out_td:
                    out_td.append(td)
                return tuple(flat)
            return inner
        return jax.lax.cond(p, run(true_fn), run(false_fn),
                            (ops, tuple(cap_vals)))

    out = apply(op_name, _fn, pred, list(operands), list(captured))
    out = out if isinstance(out, tuple) else (out,)
    return jax.tree_util.tree_unflatten(out_td[0], list(out))


def cond(pred, true_fn, false_fn, *operands):
    """Functional conditional lowered to XLA Cond (reference:
    fluid/layers/control_flow.py cond).  Differentiable through the tape
    for operands AND closure-captured tensors/layer parameters."""
    return _tape_cond(pred, true_fn, false_fn, operands)


def while_loop(cond_fn, body_fn, loop_vars, maximum_trip_count=None):
    """Functional while lowered to XLA While (reference: while_loop:1167).

    Without ``maximum_trip_count``, forward-only by backend design: XLA
    While has no static trip count, so reverse mode cannot stage the
    per-iteration residuals.  The loop rides the tape as ONE op whose
    vjp RAISES — backward through it is a loud NotImplementedError
    instead of silently-zero gradients (the reference's static While IS
    differentiable via a while_grad stack, so silence here would be
    silently-wrong training math).  Captured layer weights are promoted
    to operands exactly so that backward finds the op and fails loudly
    even when no explicit loop var requires grad.

    With ``maximum_trip_count=N`` the loop lowers to a bounded
    ``lax.scan`` of length N with a predicate mask — fully reverse-
    differentiable (the TPU-native analog of the reference's
    while_grad stack, which stages residuals dynamically).  Semantics:
    the state stops updating once the predicate goes false; if the
    predicate is still true after N trips the loop TRUNCATES at N (pick
    N as a real upper bound).  Cost is N body evaluations regardless of
    the dynamic trip count."""
    from ..core.dispatch import apply

    captured = list({**_collect_captured_params(cond_fn),
                     **_collect_captured_params(body_fn)}.values())
    meta = []

    if maximum_trip_count is not None:
        n = int(maximum_trip_count)
        if n < 0:
            raise ValueError("maximum_trip_count must be >= 0")

        def _fn_bounded(loop_vals, cap_vals):
            # canonicalize so both lax.cond branches produce identical
            # avals (python-int loop vars would come back weakly typed
            # from one branch and strongly from the other)
            init = tuple(jnp.asarray(v) for v in loop_vals)

            def run_body(state):
                with _substituted(captured, cap_vals):
                    res = body_fn(*_wrap_tree(state))
                if not isinstance(res, (tuple, list)):
                    res = (res,)
                new = tuple(_unwrap_tree(tuple(res)))
                return tuple(jnp.asarray(v).astype(s.dtype)
                             for v, s in zip(new, state))

            def step(state, _):
                with _substituted(captured, cap_vals):
                    pred = _as_raw(cond_fn(*_wrap_tree(state)))
                # lax.cond, NOT a jnp.where mask: the untaken branch's
                # vjp never runs, so a body that would produce inf/NaN
                # on the frozen post-termination state (e.g. t/(n-i))
                # cannot poison gradients with 0*inf — the classic
                # where-NaN trap — and masked-out iterations skip the
                # body's FLOPs at runtime too.
                return jax.lax.cond(pred, run_body, lambda st: st,
                                    state), None

            final, _ = jax.lax.scan(step, init, None, length=n)
            flat, td = jax.tree_util.tree_flatten(final)
            if not meta:
                meta.append(td)
            return tuple(flat)

        out = apply("jit_while_bounded", _fn_bounded, list(loop_vars),
                    list(captured))
        out = out if isinstance(out, tuple) else (out,)
        return jax.tree_util.tree_unflatten(meta[0], list(out))

    @jax.custom_vjp
    def _run(loop_raw, cap_vals):
        def with_caps(fn, vs, caps):
            with _substituted(captured, caps):
                return fn(*_wrap_tree(vs))

        def run_body(st):
            res = with_caps(body_fn, st[0], st[1])
            if not isinstance(res, (tuple, list)):
                res = (res,)  # single loop var: body may return it bare
            return tuple(_unwrap_tree(tuple(res))), st[1]

        out, _ = jax.lax.while_loop(
            lambda st: _as_raw(with_caps(cond_fn, st[0], st[1])),
            run_body, (tuple(loop_raw), tuple(cap_vals)))
        return out

    def _fwd(loop_raw, cap_vals):
        return _run(loop_raw, cap_vals), None

    def _bwd(res, ct):
        raise NotImplementedError(
            "reverse-mode gradient through jit.while_loop (or a "
            "dy2static while / for-range over a Tensor bound) is not "
            "supported: XLA While has no static trip count to stage "
            "residuals over.  Use jit.while_loop(..., "
            "maximum_trip_count=N) (bounded scan, differentiable), a "
            "python-int loop bound (unrolls at trace time), jit.scan "
            "over a fixed length, or run the loop under "
            "paddle.no_grad().")

    _run.defvjp(_fwd, _bwd)

    def _fn(loop_vals, cap_vals):
        out = _run(tuple(loop_vals), tuple(cap_vals))
        flat, td = jax.tree_util.tree_flatten(out)
        if not meta:
            meta.append(td)
        return tuple(flat)

    out = apply("jit_while", _fn, list(loop_vars), list(captured))
    out = out if isinstance(out, tuple) else (out,)
    return jax.tree_util.tree_unflatten(meta[0], list(out))


def scan(f, init, xs):
    """lax.scan with Tensor wrapping; the TPU-idiomatic loop primitive.

    Dispatched through the tape (lax.scan supports reverse mode), so
    backward through a scan reaches init/xs — matching cond.  XLA While
    (jit.while_loop) remains forward-only by backend design."""
    from ..core.dispatch import apply, no_grad_ctx

    captured = list(_collect_captured_params(f).values())
    meta = []

    def _fn(init_raw, xs_raw, cap_vals):
        def body(c, x):
            with _substituted(captured, cap_vals):
                new_c, y = f(_wrap_tree(c), _wrap_tree(x))
            return _unwrap_tree(new_c), _unwrap_tree(y)

        carry, ys = jax.lax.scan(body, init_raw, xs_raw)
        cf, ctd = jax.tree_util.tree_flatten(carry)
        yf, ytd = jax.tree_util.tree_flatten(ys)
        if not meta:
            meta.append((len(cf), ctd, ytd))
        return tuple(cf) + tuple(yf)

    out = apply("jit_scan", _fn, init, xs, list(captured))
    out = out if isinstance(out, tuple) else (out,)
    n, ctd, ytd = meta[0]
    return (jax.tree_util.tree_unflatten(ctd, list(out[:n])),
            jax.tree_util.tree_unflatten(ytd, list(out[n:])))


# ------------------------------------------------------------- save / load
def save(layer, path, input_spec=None, **configs):
    """Export for serving: serialized StableHLO + weights in one artifact
    (reference: paddle.jit.save → inference program + persistables)."""
    import pickle

    if isinstance(layer, Layer):
        layer.eval()
        fn = layer.forward
        state = {k: np.asarray(v.numpy())
                 for k, v in layer.state_dict().items()}
    else:
        fn = layer
        state = {}
    if isinstance(fn, StaticFunction):
        fn = fn._trace_target()
    else:
        # the export trace needs the same dy2static pass as to_static:
        # a tensor-condition `if`/loop in forward must lower to XLA
        # Cond/While, not hit a trace-time bool conversion
        from . import dy2static

        try:
            fn = dy2static.convert_function(fn)
        except Exception:  # noqa: BLE001 — fall back to the raw fn
            pass
    if input_spec is None:
        raise ValueError("jit.save requires input_spec")

    shapes = [jax.ShapeDtypeStruct(
        tuple(d if d and d > 0 else 1 for d in spec.shape),
        to_np(spec.dtype)) for spec in input_spec]

    def pure_fn(*arg_vals):
        with dispatch.no_grad_ctx(), dispatch.static_trace_guard():
            args = [Tensor(v) for v in arg_vals]
            out = fn(*args)
        return jax.tree_util.tree_map(
            lambda x: x._value if isinstance(x, Tensor) else x, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    exported = jax.export.export(jax.jit(pure_fn))(*shapes)
    blob = {
        "stablehlo": exported.serialize(),
        "state": state,
        "input_spec": [(list(s.shape), str(s.dtype)) for s in shapes],
    }
    fname = path if path.endswith(".pdmodel") else path + ".pdmodel"
    with open(fname, "wb") as f:
        pickle.dump(blob, f, protocol=4)
    return fname


class LoadedFunction:
    """Deserialized serving artifact; __call__ runs the compiled program."""

    def __init__(self, exported, state):
        self._exported = exported
        self._state = state

    def __call__(self, *args):
        raw = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
               for a in args]
        out = self._exported.call(*raw)
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if _is_arrayish(v) else v, out)

    def eval(self):
        return self

    def state_dict(self):
        return self._state


def load(path, **configs):
    import pickle

    fname = path if path.endswith(".pdmodel") else path + ".pdmodel"
    with open(fname, "rb") as f:
        blob = pickle.load(f)
    exported = jax.export.deserialize(blob["stablehlo"])
    return LoadedFunction(exported, blob["state"])


# ---------------------------------------------------------------------------
# reference-compat surface (python/paddle/fluid/dygraph/jit.py,
# dygraph_to_static/program_translator.py)
# ---------------------------------------------------------------------------

declarative = to_static  # the reference's older decorator name


class ProgramTranslator:
    """Singleton toggling dy2static globally (reference:
    program_translator.py ProgramTranslator.get_instance().enable(False)).
    Here 'static conversion' is whole-step XLA compilation: disabling it
    makes to_static-wrapped functions run eagerly."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        ProgramTranslator._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return ProgramTranslator._enabled


def enable_to_static(flag: bool):
    ProgramTranslator.get_instance().enable(flag)


def set_verbosity(level=0, also_to_stdout=False):
    """Dy2static logging verbosity (reference: logging_utils.set_verbosity).
    Maps onto the jit logger level."""
    import logging

    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)
    return level


def set_code_level(level=100, also_to_stdout=False):
    """Parity shim: the reference prints transformed AST at this level;
    we have no AST transform stage (tracing does the conversion), so this
    records the setting only."""
    set_verbosity(1 if level else 0)
    return level


class TracedLayer:
    """Trace-and-replay wrapper (reference: fluid/dygraph/jit.py
    TracedLayer over program_desc_tracing): trace builds the compiled
    callable; save_inference_model exports it."""

    def __init__(self, layer, static_fn, example_args):
        self._layer = layer
        self._fn = static_fn
        self._example_args = example_args

    @staticmethod
    def trace(layer, inputs):
        fn = to_static(lambda *a: layer(*a))
        outs = fn(*inputs)
        return outs, TracedLayer(layer, fn, inputs)

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        save(self._layer, path, input_spec=list(self._example_args))
        return path


# reference name for what jit.load returns (fluid/dygraph/io.py
# TranslatedLayer); LoadedFunction is the implementation
TranslatedLayer = LoadedFunction
