"""ctypes binding for the native host event recorder
(paddle_tpu/core/native/host_tracer.cc — reference:
paddle/fluid/platform/profiler/host_event_recorder.h).

Event begin/end on the hot path happens in C++ (clock read + vector push);
Python only interns names once and drains snapshots at profiler stop.

When the native library cannot be loaded (no compiler in the container,
unsupported platform), a pure-Python :class:`_PyRecorder` takes over with
the SAME semantics — ``begin``/``end`` gated by the enable flag, ``emit``
unconditional, per-thread open-range stacks, one shared intern table —
so host ranges degrade to slower instead of silently vanishing
(``available()`` still reports only the native path; use
:func:`fallback_active` to detect the degraded mode).
"""
from __future__ import annotations

import ctypes
import threading
import time
from typing import Dict, List, Tuple

_lib = None
_lib_failed = False
_intern_cache: dict = {}
_py_recorder = None


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        from ..core.native.build import load_native

        lib = load_native("host_tracer")
        lib.ht_intern.restype = ctypes.c_uint32
        lib.ht_intern.argtypes = [ctypes.c_char_p]
        lib.ht_enable.argtypes = [ctypes.c_int]
        lib.ht_enabled.restype = ctypes.c_int
        lib.ht_begin.argtypes = [ctypes.c_uint32]
        lib.ht_emit.argtypes = [ctypes.c_uint32, ctypes.c_uint64,
                                ctypes.c_uint64]
        lib.ht_now_ns.restype = ctypes.c_uint64
        lib.ht_snapshot.restype = ctypes.c_uint64
        lib.ht_read.argtypes = [ctypes.c_uint64,
                                ctypes.POINTER(ctypes.c_uint32),
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64)]
        lib.ht_name.restype = ctypes.c_uint32
        lib.ht_name.argtypes = [ctypes.c_uint32, ctypes.c_char_p,
                                ctypes.c_uint32]
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


class _PyRecorder:
    """Pure-Python stand-in for host_tracer.cc: same intern-table and
    per-thread buffer design, one process-wide lock instead of the
    native per-thread mutexes (the fallback trades hot-path cost for
    existing at all)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._intern: Dict[str, int] = {}
        self._names: List[str] = []
        # tid -> closed events [(name_id, start_ns, end_ns)] / open stack
        self._events: Dict[int, List[Tuple[int, int, int]]] = {}
        self._open: Dict[int, List[Tuple[int, int]]] = {}
        self.enabled = False

    def intern(self, name: str) -> int:
        with self._lock:
            nid = self._intern.get(name)
            if nid is None:
                nid = len(self._names)
                self._names.append(name)
                self._intern[name] = nid
            return nid

    # begin/end honor the enable gate, exactly like ht_begin/ht_end
    def begin(self, name_id: int):
        if not self.enabled:
            return
        tid = threading.get_native_id()
        with self._lock:
            self._open.setdefault(tid, []).append(
                (name_id, time.perf_counter_ns()))

    def end(self):
        if not self.enabled:
            return
        tid = threading.get_native_id()
        with self._lock:
            stack = self._open.get(tid)
            if not stack:
                return
            name_id, start = stack.pop()
            self._events.setdefault(tid, []).append(
                (name_id, start, time.perf_counter_ns()))

    # emit records unconditionally, exactly like ht_emit
    def emit(self, name_id: int, start_ns: int, end_ns: int):
        tid = threading.get_native_id()
        with self._lock:
            self._events.setdefault(tid, []).append(
                (name_id, start_ns, end_ns))

    def drain(self) -> List[Tuple[int, str, int, int, str]]:
        with self._lock:
            out = [(tid, self._names[nid], s, e, "host")
                   for tid, events in self._events.items()
                   for nid, s, e in events]
            self._events.clear()
            return out


def _fallback() -> _PyRecorder:
    global _py_recorder
    if _py_recorder is None:
        _py_recorder = _PyRecorder()
        # ids handed out before the load failure belong to no table;
        # restart interning so fallback ids stay self-consistent
        _intern_cache.clear()
    return _py_recorder


def available() -> bool:
    """True only for the NATIVE recorder (the fallback is always
    available; see :func:`fallback_active`)."""
    return _load() is not None


def fallback_active() -> bool:
    """True once the pure-Python recorder has taken over."""
    return _py_recorder is not None and _load() is None


def intern(name: str) -> int:
    nid = _intern_cache.get(name)
    if nid is None:
        lib = _load()
        if lib is None:
            nid = _fallback().intern(name)
        else:
            nid = lib.ht_intern(name.encode())
        _intern_cache[name] = nid
    return nid


def enable(on: bool = True):
    lib = _load()
    if lib is not None:
        lib.ht_enable(1 if on else 0)
    else:
        _fallback().enabled = bool(on)


def emit(name: str, start_ns: int, end_ns: int):
    lib = _load()
    if lib is not None:
        lib.ht_emit(intern(name), start_ns, end_ns)
    else:
        _fallback().emit(intern(name), start_ns, end_ns)


def begin(name: str):
    lib = _load()
    if lib is not None:
        lib.ht_begin(intern(name))
    else:
        _fallback().begin(intern(name))


def end():
    lib = _load()
    if lib is not None:
        lib.ht_end()
    else:
        _fallback().end()


def drain() -> List[Tuple[int, str, int, int, str]]:
    """(tid, name, start_ns, end_ns, 'host') tuples, clearing the buffers."""
    lib = _load()
    if lib is None:
        return _fallback().drain()
    n = lib.ht_snapshot()
    out = []
    name_id = ctypes.c_uint32()
    tid = ctypes.c_uint64()
    s = ctypes.c_uint64()
    e = ctypes.c_uint64()
    buf = ctypes.create_string_buffer(512)
    names: dict = {}
    for i in range(n):
        lib.ht_read(i, ctypes.byref(name_id), ctypes.byref(tid),
                    ctypes.byref(s), ctypes.byref(e))
        nm = names.get(name_id.value)
        if nm is None:
            ln = lib.ht_name(name_id.value, buf, 512)
            nm = buf.raw[:ln].decode(errors="replace")
            names[name_id.value] = nm
        out.append((tid.value, nm, s.value, e.value, "host"))
    return out
