"""ctypes binding for the native host event recorder
(paddle_tpu/core/native/host_tracer.cc — reference:
paddle/fluid/platform/profiler/host_event_recorder.h).

Event begin/end on the hot path happens in C++ (clock read + vector push);
Python only interns names once and drains snapshots at profiler stop.
"""
from __future__ import annotations

import ctypes
from typing import List, Tuple

_lib = None
_lib_failed = False
_intern_cache: dict = {}


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        from ..core.native.build import load_native

        lib = load_native("host_tracer")
        lib.ht_intern.restype = ctypes.c_uint32
        lib.ht_intern.argtypes = [ctypes.c_char_p]
        lib.ht_enable.argtypes = [ctypes.c_int]
        lib.ht_enabled.restype = ctypes.c_int
        lib.ht_begin.argtypes = [ctypes.c_uint32]
        lib.ht_emit.argtypes = [ctypes.c_uint32, ctypes.c_uint64,
                                ctypes.c_uint64]
        lib.ht_now_ns.restype = ctypes.c_uint64
        lib.ht_snapshot.restype = ctypes.c_uint64
        lib.ht_read.argtypes = [ctypes.c_uint64,
                                ctypes.POINTER(ctypes.c_uint32),
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64)]
        lib.ht_name.restype = ctypes.c_uint32
        lib.ht_name.argtypes = [ctypes.c_uint32, ctypes.c_char_p,
                                ctypes.c_uint32]
        _lib = lib
    except Exception:
        _lib_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def intern(name: str) -> int:
    nid = _intern_cache.get(name)
    if nid is None:
        lib = _load()
        if lib is None:
            return 0
        nid = lib.ht_intern(name.encode())
        _intern_cache[name] = nid
    return nid


def enable(on: bool = True):
    lib = _load()
    if lib is not None:
        lib.ht_enable(1 if on else 0)


def emit(name: str, start_ns: int, end_ns: int):
    lib = _load()
    if lib is not None:
        lib.ht_emit(intern(name), start_ns, end_ns)


def begin(name: str):
    lib = _load()
    if lib is not None:
        lib.ht_begin(intern(name))


def end():
    lib = _load()
    if lib is not None:
        lib.ht_end()


def drain() -> List[Tuple[int, str, int, int, str]]:
    """(tid, name, start_ns, end_ns, 'host') tuples, clearing the buffers."""
    lib = _load()
    if lib is None:
        return []
    n = lib.ht_snapshot()
    out = []
    name_id = ctypes.c_uint32()
    tid = ctypes.c_uint64()
    s = ctypes.c_uint64()
    e = ctypes.c_uint64()
    buf = ctypes.create_string_buffer(512)
    names: dict = {}
    for i in range(n):
        lib.ht_read(i, ctypes.byref(name_id), ctypes.byref(tid),
                    ctypes.byref(s), ctypes.byref(e))
        nm = names.get(name_id.value)
        if nm is None:
            ln = lib.ht_name(name_id.value, buf, 512)
            nm = buf.raw[:ln].decode(errors="replace")
            names[name_id.value] = nm
        out.append((tid.value, nm, s.value, e.value, "host"))
    return out
