# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.profiler (reference: python/paddle/profiler/profiler.py:270 +
platform/profiler/ host tracer + CUPTI).

TPU-native: host ranges recorded with perf_counter_ns (the HostTraceLevel
analog); device activity comes from jax.profiler (XLA/Xprof) traces.  Export
keeps the chrome://tracing JSON format the reference emits
(chrometracing_logger.cc).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, List, Optional

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "current_profiler", "record_host_range"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class _HostEventRecorder:
    """Lock-free-ish per-thread buffers (reference: host_event_recorder.h)."""

    def __init__(self):
        self._local = threading.local()
        self._all_buffers = []
        self._lock = threading.Lock()
        self._native = None  # None = undecided, False = python fallback

    def _buffer(self):
        buf = getattr(self._local, "buf", None)
        if buf is None:
            buf = []
            self._local.buf = buf
            with self._lock:
                # OS thread id, same namespace as the native tracer's
                # SYS_gettid, so both sources merge per-thread.
                self._all_buffers.append((threading.get_native_id(), buf))
        return buf

    def record(self, name, start_ns, end_ns, category="host"):
        # Prefer the native recorder (core/native/host_tracer.cc) for the
        # default category: the hot path is a C++ clock read + push.  The
        # native buffer carries no category, so non-host events stay on the
        # Python buffer.  The native-vs-fallback decision is resolved once.
        if self._native is None:
            from . import host_tracer

            self._native = host_tracer if host_tracer.available() else False
        if self._native and category == "host":
            self._native.emit(name, start_ns, end_ns)
        else:
            self._buffer().append((name, start_ns, end_ns, category))

    def drain(self):
        # Only touch the native tracer if it was actually used for
        # recording — host_tracer.drain() JIT-compiles the C++ library on
        # first use, which must not be triggered by merely stopping a
        # session that recorded nothing natively.
        out = []
        if self._native:
            from . import host_tracer

            out = list(host_tracer.drain())
        else:
            from . import host_tracer

            # events recorded through host_tracer's pure-Python fallback
            # (direct begin/end/emit users while the native lib is
            # unavailable) merge here; fallback_active() short-circuits
            # before _load(), so this never triggers the JIT compile
            if host_tracer.fallback_active():
                out = list(host_tracer.drain())
        with self._lock:
            for tid, buf in self._all_buffers:
                out.extend((tid,) + e for e in buf)
                buf.clear()
        return out


_recorder = _HostEventRecorder()
_active_profiler: Optional["Profiler"] = None


def current_profiler() -> Optional["Profiler"]:
    """The active Profiler session, or None.  External event sources
    (e.g. serving metrics) use this to emit host ranges only while a
    session is actually recording."""
    return _active_profiler


def record_host_range(name: str, start_ns: int, end_ns: int,
                      category: str = "host"):
    """Record an explicit host range with caller-measured timestamps
    (perf_counter_ns).  Lands in the active session's chrome trace next
    to RecordEvent ranges; categories other than "host" stay on the
    Python buffer so they keep their category at export."""
    _recorder.record(name, start_ns, end_ns, category=category)


class RecordEvent:
    """Annotated host range (reference: event_tracing.h RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._start = None

    def begin(self):
        self._start = time.perf_counter_ns()

    def end(self):
        if self._start is not None:
            _recorder.record(self.name, self._start, time.perf_counter_ns())
            self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


def make_scheduler(closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int],
                                                                     ProfilerState]:
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(
            dir_name, f"{worker_name or 'worker'}_{int(time.time())}.json")
        prof._export_chrome(fname)
        return fname

    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        self.targets = targets or [ProfilerTarget.CPU]
        if scheduler is None:
            self.scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, tuple):
            lo, hi = scheduler
            self.scheduler = lambda step: (
                ProfilerState.RECORD if lo <= step < hi
                else ProfilerState.CLOSED)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self.events: List[tuple] = []
        self._step_times: List[float] = []
        self._last_step_t = None
        self._jax_trace_dir = None
        # [(host_anchor_ns, [chrome events])] — one segment per record
        # window, each rebased with ITS OWN anchor at export
        self._device_segments: List[tuple] = []
        self._device_anchor_ns = None

    # -- lifecycle
    def start(self):
        self.state = self.scheduler(self.step_num)
        self._maybe_start_device_trace()
        self._last_step_t = time.perf_counter()
        global _active_profiler
        _active_profiler = self

    def stop(self):
        self.events.extend(_recorder.drain())
        self._maybe_stop_device_trace()
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)
        global _active_profiler
        _active_profiler = None
        self.state = ProfilerState.CLOSED

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self.events.extend(_recorder.drain())
        self.step_num += 1
        new_state = self.scheduler(self.step_num)
        if new_state != self.state:
            if new_state == ProfilerState.CLOSED:
                self._maybe_stop_device_trace()
            elif self.state == ProfilerState.CLOSED:
                self._maybe_start_device_trace()
            self.state = new_state

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False

    # -- device (XLA) trace via jax.profiler
    def _maybe_start_device_trace(self):
        if ProfilerTarget.TPU in self.targets and \
                self.state in (ProfilerState.RECORD,
                               ProfilerState.RECORD_AND_RETURN):
            import tempfile

            import jax

            self._jax_trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_trace_")
            try:
                jax.profiler.start_trace(self._jax_trace_dir)
                self._device_anchor_ns = time.perf_counter_ns()
            except Exception:
                self._jax_trace_dir = None

    def _maybe_stop_device_trace(self):
        if self._jax_trace_dir is not None:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._collect_device_events(self._jax_trace_dir)
            self._jax_trace_dir = None

    def _collect_device_events(self, trace_dir):
        """Pull the XLA profiler's chrome events (the *.trace.json.gz the
        PJRT profiler session writes next to the xplane.pb) into this
        profiler, so export() emits ONE file with host + device lanes —
        the reference's merged event tree (platform/profiler/
        chrometracing_logger.cc) instead of two disconnected dirs."""
        import glob
        import gzip

        events = []
        for path in glob.glob(os.path.join(
                trace_dir, "plugins", "profile", "*", "*.trace.json.gz")):
            try:
                with gzip.open(path, "rt") as f:
                    payload = json.load(f)
            except Exception:
                continue
            events.extend(payload.get("traceEvents", []))
        if events:
            self._device_segments.append((self._device_anchor_ns, events))

    # -- reporting
    def _export_chrome(self, path):
        trace_events = []
        host_pid = os.getpid()
        for tid, name, start_ns, end_ns, cat in self.events:
            trace_events.append({
                "name": name, "cat": cat, "ph": "X",
                "ts": start_ns / 1000.0, "dur": (end_ns - start_ns) / 1000.0,
                "pid": host_pid, "tid": tid,
            })
        trace_events.append({
            "ph": "M", "name": "process_name", "pid": host_pid,
            "args": {"name": "host (paddle_tpu ranges)"}})
        # device lanes ride under their own pids, rebased PER RECORD
        # WINDOW so the two clock domains land on one timeline: each
        # segment's earliest timestamp is pinned to the host
        # perf_counter moment ITS start_trace returned (a global shift
        # would stack multi-window traces on top of each other)
        pid_off = host_pid + 100000
        for anchor_ns, events in self._device_segments:
            ts_events = [e for e in events if "ts" in e]
            shift = 0.0
            if ts_events and anchor_ns is not None:
                shift = (anchor_ns / 1000.0
                         - min(float(e["ts"]) for e in ts_events))
            for e in events:
                e = dict(e)
                if "ts" in e:
                    e["ts"] = float(e["ts"]) + shift
                if "pid" in e:
                    try:
                        e["pid"] = int(e["pid"]) + pid_off
                    except (TypeError, ValueError):
                        pass
                trace_events.append(e)
        with open(path, "w") as f:
            json.dump({"traceEvents": trace_events}, f)
        return path

    def export(self, path, format="json"):
        return self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        agg = {}
        for tid, name, start_ns, end_ns, cat in self.events:
            d = agg.setdefault(name, [0, 0.0, 0.0, float("inf")])
            dur = (end_ns - start_ns) / 1e6
            d[0] += 1
            d[1] += dur
            d[2] = max(d[2], dur)
            d[3] = min(d[3], dur)
        # SortedKeys: host-range stats (the GPU* keys of the reference map
        # onto the same host table here — device timing lives in the
        # Xplane trace jax.profiler captures)
        sort_fns = {
            None: lambda kv: -kv[1][1],
            SortedKeys.CPUTotal: lambda kv: -kv[1][1],
            SortedKeys.GPUTotal: lambda kv: -kv[1][1],
            SortedKeys.CPUAvg: lambda kv: -(kv[1][1] / kv[1][0]),
            SortedKeys.GPUAvg: lambda kv: -(kv[1][1] / kv[1][0]),
            SortedKeys.CPUMax: lambda kv: -kv[1][2],
            SortedKeys.GPUMax: lambda kv: -kv[1][2],
            SortedKeys.CPUMin: lambda kv: kv[1][3],
            SortedKeys.GPUMin: lambda kv: kv[1][3],
        }
        key_fn = sort_fns.get(sorted_by, sort_fns[None])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"
                 f"{'Max(ms)':>12}{'Min(ms)':>12}"]
        for name, (calls, total, mx, mn) in sorted(agg.items(), key=key_fn):
            lines.append(f"{name:<40}{calls:>8}{total:>12.3f}"
                         f"{total / calls:>12.3f}{mx:>12.3f}{mn:>12.3f}")
        if self._step_times:
            import numpy as np

            lines.append(f"steps: {len(self._step_times)}, avg "
                         f"{np.mean(self._step_times) * 1000:.2f}ms")
        report = "\n".join(lines)
        print(report)
        return report


def load_profiler_result(filename):
    with open(filename) as f:
        return json.load(f)


class benchmark:
    """paddle.profiler.benchmark timer (ips) analog."""

    def __init__(self):
        self._times = []
        self._t = None

    def begin(self):
        self._t = time.perf_counter()

    def end(self, num_samples=1):
        if self._t is not None:
            self._times.append((time.perf_counter() - self._t, num_samples))

    def ips(self):
        total_t = sum(t for t, _ in self._times)
        total_n = sum(n for _, n in self._times)
        return total_n / total_t if total_t else 0.0


class SortedKeys:
    """Summary-table sort orders (reference: profiler/profiler_statistic.py
    SortedKeys enum)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name: str, worker_name: str = None):
    """Post-run exporter hook (reference: profiler.export_protobuf writes
    the profiler result protobuf).  The device half of our trace already
    lands as Xplane protobufs under jax.profiler's log dir; the host
    ranges export as chrome-trace JSON (the reference's .pb wire format
    is paddle-internal) — same behavior as export_chrome_tracing,
    including the timestamp suffix that keeps runs from clobbering each
    other."""
    return export_chrome_tracing(dir_name, worker_name)
