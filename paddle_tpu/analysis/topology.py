"""Multi-host topology model for the static shard planner.

The shard planner (shardplan.py) prices every collective against a
single flat interconnect — correct for one host, wrong the moment a
mesh axis spans hosts: inter-host traffic rides the data-center
network (DCN), which is an order of magnitude slower than ICI in both
bandwidth and latency (``ChipProfile.dcn_*`` vs ``ici_*``).

A :class:`Topology` describes ``hosts × chips_per_host`` plus an
axis→link-level assignment (``"ici"`` or ``"dcn"``).  Under it every
planned collective whose mesh axes span hosts is **decomposed
hierarchically** into per-link phases — the standard multislice
lowering:

    all_reduce(S)      → reduce_scatter(S, ici) + all_reduce(S/n_i, dcn)
                         + all_gather(S, ici)
    all_gather(S)      → all_gather(S/n_i, dcn) + all_gather(S, ici)
    reduce_scatter(S)  → reduce_scatter(S, ici) + reduce_scatter(S/n_i, dcn)
    all_to_all(S)      → all_to_all(S, dcn) + all_to_all(S, ici)
    ppermute(S)        → ppermute(S, dcn)   (a synchronous ring hop is
                         gated by its slowest edge — one DCN factor on
                         the axis makes the whole hop a DCN hop)

where ``n_i``/``n_d`` are the ICI/DCN factor products of the
collective's axes.  Each phase is priced with the same ring formulas
the flat planner uses (all_reduce moves ``2·S·(n−1)/n`` per chip, the
others ``S·(n−1)/n``) against the matching link profile.  The DCN-side
all_reduce runs on the ``S/n_i`` shard the intra-host reduce_scatter
left behind — that payload reduction is the whole point of the
hierarchical decomposition.

The :func:`recommend_layouts` recommender enumerates every valid
axis→level assignment for a mesh, reprices a step's flat collective
inventory under each, and returns them ranked by total comm time — the
static answer to "which axis should I put on DCN".
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "LinkPhase",
    "RankedLayout",
    "Topology",
    "format_recommendations",
]

ICI = "ici"
DCN = "dcn"


@dataclasses.dataclass(frozen=True)
class LinkPhase:
    """One link-level phase of a decomposed collective: the planner
    turns each into a priced ``Collective`` carrying ``level``."""

    kind: str                  # all_reduce | all_gather | ...
    level: str                 # "ici" | "dcn"
    axes: Tuple[str, ...]      # participating mesh axes at this level
    payload_bytes: float       # logical payload entering this phase
    factor: float              # ring factor: wire bytes = payload·factor


@dataclasses.dataclass(frozen=True)
class Topology:
    """``hosts`` × ``chips_per_host`` and the axis→link assignment.

    ``chips_per_host`` is the per-host ICI grid shape, e.g. ``(2, 2)``
    for a 4-chip host; the grid shape only labels the intra-host
    fabric (ICI pricing is per-chip aggregate), its *product* is what
    budgets use.  ``axis_levels`` pins mesh axes to ``"ici"`` or
    ``"dcn"``; unpinned axes are assigned by :meth:`splits` — walking
    the mesh in order, axes go to DCN until the DCN factor product
    covers ``hosts``, the rest stay on ICI (the multislice default:
    outermost/data axis crosses hosts).
    """

    hosts: int = 1
    chips_per_host: Tuple[int, ...] = (4,)
    axis_levels: Mapping[str, str] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if int(self.hosts) < 1:
            raise ValueError(f"hosts must be >= 1, got {self.hosts}")
        for ax, lvl in dict(self.axis_levels).items():
            if lvl not in (ICI, DCN):
                raise ValueError(
                    f"axis_levels[{ax!r}] must be 'ici' or 'dcn', "
                    f"got {lvl!r}")

    @property
    def chips_per_host_count(self) -> int:
        n = 1
        for d in self.chips_per_host:
            n *= int(d)
        return n

    @property
    def total_chips(self) -> int:
        return int(self.hosts) * self.chips_per_host_count

    # -- axis factor splits --------------------------------------------------

    def splits(self, mesh: Dict[str, int]) -> Dict[str, Tuple[int, int]]:
        """axis → ``(n_ici, n_dcn)`` factor split.  A DCN-assigned axis
        of size ``s`` contributes ``gcd(s, remaining_hosts)`` to the
        DCN level (an axis larger than the host count spans: part of it
        crosses hosts, the rest stays intra-host); pinned axes consume
        DCN capacity first, then unpinned axes in mesh order."""
        mesh = {str(k): int(v) for k, v in mesh.items()}
        out: Dict[str, Tuple[int, int]] = {}
        remaining = int(self.hosts)

        def take(size: int, remaining: int) -> Tuple[int, int]:
            n_d = math.gcd(size, remaining) if remaining > 1 else 1
            return size // n_d, n_d

        for ax, size in mesh.items():
            lvl = self.axis_levels.get(ax)
            if lvl == DCN:
                n_i, n_d = take(size, remaining)
                out[ax] = (n_i, n_d)
                remaining //= n_d
            elif lvl == ICI:
                out[ax] = (size, 1)
        for ax, size in mesh.items():
            if ax in out:
                continue
            n_i, n_d = take(size, remaining)
            out[ax] = (n_i, n_d)
            remaining //= n_d
        return out

    def validate(self, mesh: Dict[str, int]):
        """Raise ValueError when the mesh cannot be laid onto this
        topology: total chips must match hosts × chips/host, and the
        DCN factor product must cover every host (a mesh spanning only
        part of the fleet means dead hosts the plan would not see)."""
        mesh = {str(k): int(v) for k, v in mesh.items()}
        n = 1
        for v in mesh.values():
            n *= v
        if n != self.total_chips:
            raise ValueError(
                f"mesh {mesh} has {n} chips but the topology is "
                f"{self.hosts} host(s) × {self.chips_per_host_count} "
                f"chips/host = {self.total_chips}")
        splits = self.splits(mesh)
        dcn_product = 1
        for n_i, n_d in splits.values():
            dcn_product *= n_d
        if dcn_product != self.hosts:
            raise ValueError(
                f"axis→level assignment spans {dcn_product} of "
                f"{self.hosts} hosts (splits {splits}) — no axis "
                "factorization crosses the remaining hosts; assign a "
                "host-divisible axis to 'dcn' or fix the mesh")

    def level_of(self, axis: str, mesh: Dict[str, int]) -> str:
        """The link level ``axis`` lands on (``"dcn"`` when any factor
        of it crosses hosts)."""
        n_i, n_d = self.splits(mesh).get(axis, (1, 1))
        return DCN if n_d > 1 else ICI

    # -- hierarchical decomposition ------------------------------------------

    def phases(self, kind: str, axes: Sequence[str], payload: float,
               mesh: Dict[str, int],
               factor: Optional[float] = None) -> List[LinkPhase]:
        """Decompose one flat collective into priced link phases.

        ``factor`` overrides the ring factor for kinds the flat planner
        priced specially (ppermute's per-hop 1.0).
        """
        splits = self.splits(mesh)
        axes = tuple(a for a in axes if mesh.get(a, 1) > 1)
        ici_axes = tuple(a for a in axes if splits.get(a, (1, 1))[0] > 1)
        dcn_axes = tuple(a for a in axes if splits.get(a, (1, 1))[1] > 1)
        n_i = 1
        n_d = 1
        for a in axes:
            s = splits.get(a, (mesh.get(a, 1), 1))
            n_i *= s[0]
            n_d *= s[1]

        def ring(kind: str, n: int) -> float:
            return 2.0 * (n - 1) / n if kind == "all_reduce" \
                else (n - 1) / n

        if kind == "ppermute":
            # a synchronous neighbour-exchange ring step completes when
            # its slowest edge does: any DCN factor on the axis makes
            # the hop DCN-priced end to end
            level = DCN if n_d > 1 else ICI
            return [LinkPhase("ppermute", level, axes, payload,
                              1.0 if factor is None else factor)]
        if n_d <= 1:
            return [LinkPhase(kind, ICI, axes, payload,
                              ring(kind, n_i) if factor is None
                              else factor)]
        if n_i <= 1:
            return [LinkPhase(kind, DCN, axes, payload,
                              ring(kind, n_d) if factor is None
                              else factor)]
        if kind == "all_reduce":
            return [
                LinkPhase("reduce_scatter", ICI, ici_axes, payload,
                          (n_i - 1) / n_i),
                LinkPhase("all_reduce", DCN, dcn_axes, payload / n_i,
                          2.0 * (n_d - 1) / n_d),
                LinkPhase("all_gather", ICI, ici_axes, payload,
                          (n_i - 1) / n_i),
            ]
        if kind == "all_gather":
            # DCN leg first, on the smallest shard — each host gathers
            # the missing inter-host shards over DCN, then broadcasts
            # intra-host over ICI
            return [
                LinkPhase("all_gather", DCN, dcn_axes, payload / n_i,
                          (n_d - 1) / n_d),
                LinkPhase("all_gather", ICI, ici_axes, payload,
                          (n_i - 1) / n_i),
            ]
        if kind == "reduce_scatter":
            return [
                LinkPhase("reduce_scatter", ICI, ici_axes, payload,
                          (n_i - 1) / n_i),
                LinkPhase("reduce_scatter", DCN, dcn_axes,
                          payload / n_i, (n_d - 1) / n_d),
            ]
        if kind == "all_to_all":
            # the (n_d−1)/n_d fraction of each chip's payload targets
            # other hosts and rides DCN; the intra-host remainder is an
            # ICI exchange over the ici factor
            return [
                LinkPhase("all_to_all", DCN, dcn_axes, payload,
                          (n_d - 1) / n_d),
                LinkPhase("all_to_all", ICI, ici_axes, payload,
                          (n_i - 1) / n_i),
            ]
        # unknown kind spanning hosts: conservatively price the whole
        # payload on the slow link so the plan never under-counts DCN
        return [LinkPhase(kind, DCN, axes, payload,
                          ring("other", n_d * n_i) if factor is None
                          else factor)]


# ---------------------------------------------------------------------------
# layout recommender
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RankedLayout:
    """One enumerated axis→level assignment, priced against a step."""

    assignment: Tuple[Tuple[str, str], ...]   # ((axis, level), ...)
    topology: Topology
    ici_bytes: float
    dcn_bytes: float
    comm_time_s: float

    @property
    def dcn_axes(self) -> Tuple[str, ...]:
        return tuple(a for a, lvl in self.assignment if lvl == DCN)

    def describe(self) -> str:
        dcn = ",".join(self.dcn_axes) or "<none>"
        return (f"dcn={dcn:<12} comm {self.comm_time_s * 1e6:9.1f} µs  "
                f"(ICI {self.ici_bytes / 1024:9.1f} KiB, "
                f"DCN {self.dcn_bytes / 1024:9.1f} KiB)")


def enumerate_topologies(mesh: Dict[str, int], hosts: int,
                         chips_per_host: Optional[Tuple[int, ...]] = None
                         ) -> List[Topology]:
    """Every distinct axis→level assignment whose DCN product covers
    ``hosts`` exactly.  Assignments where a DCN-pinned axis contributes
    no DCN factor (gcd 1) duplicate a smaller subset and are skipped."""
    mesh = {str(k): int(v) for k, v in mesh.items()}
    if chips_per_host is None:
        total = 1
        for v in mesh.values():
            total *= v
        if total % hosts:
            raise ValueError(
                f"mesh {mesh} ({total} chips) is not divisible by "
                f"{hosts} hosts")
        chips_per_host = (total // hosts,)
    axes = [a for a, s in mesh.items() if s > 1]
    out: List[Topology] = []
    seen = set()
    for r in range(len(axes) + 1):
        for subset in itertools.combinations(axes, r):
            levels = {a: (DCN if a in subset else ICI) for a in axes}
            topo = Topology(hosts=hosts, chips_per_host=chips_per_host,
                            axis_levels=levels)
            splits = topo.splits(mesh)
            if any(splits[a][1] == 1 for a in subset):
                continue  # a pinned axis got no DCN factor: degenerate
            product = 1
            for n_i, n_d in splits.values():
                product *= n_d
            if product != hosts:
                continue
            key = tuple(sorted((a, splits[a][1]) for a in subset))
            if key in seen:
                continue
            seen.add(key)
            out.append(topo)
    return out


def rank_layouts(flat_collectives, mesh: Dict[str, int], chip,
                 hosts: int,
                 chips_per_host: Optional[Tuple[int, ...]] = None
                 ) -> List[RankedLayout]:
    """Reprice a step's *flat* collective inventory under every valid
    axis→level assignment and rank by total comm time (ties: least DCN
    bytes).  Repricing reuses the propagation result — no re-trace."""
    from .xray import estimate_collective_time

    ranked: List[RankedLayout] = []
    for topo in enumerate_topologies(mesh, hosts, chips_per_host):
        splits = topo.splits(mesh)
        ici_b = dcn_b = time_s = 0.0
        for c in flat_collectives:
            pay = float(c.payload_bytes)
            factor = (c.bytes_moved / pay
                      if c.kind == "ppermute" and pay else None)
            for ph in topo.phases(c.kind, c.axes, pay, mesh,
                                  factor=factor):
                moved = ph.payload_bytes * ph.factor
                time_s += estimate_collective_time(
                    moved, chip, level=ph.level) * c.count
                if ph.level == DCN:
                    dcn_b += moved * c.count
                else:
                    ici_b += moved * c.count
        assignment = tuple(
            (a, DCN if splits[a][1] > 1 else ICI)
            for a in mesh if mesh[a] > 1)
        ranked.append(RankedLayout(
            assignment=assignment, topology=topo, ici_bytes=ici_b,
            dcn_bytes=dcn_b, comm_time_s=time_s))
    ranked.sort(key=lambda r: (r.comm_time_s, r.dcn_bytes,
                               r.assignment))
    return ranked


def format_recommendations(ranked: Sequence[RankedLayout],
                           top: int = 8) -> str:
    """Ranked table for the CLI: best assignment first."""
    rows = [f"{'rank':<6}{'dcn axes':<14}{'comm µs':>10}"
            f"{'ICI KiB':>12}{'DCN KiB':>12}"]
    for i, r in enumerate(ranked[:top]):
        dcn = ",".join(r.dcn_axes) or "<none>"
        rows.append(f"{i + 1:<6}{dcn:<14}"
                    f"{r.comm_time_s * 1e6:>10.1f}"
                    f"{r.ici_bytes / 1024:>12.1f}"
                    f"{r.dcn_bytes / 1024:>12.1f}")
    return "\n".join(rows)
