"""Structural Program verifier (reference: the IR graph validation under
paddle/fluid/framework/ir/ — Graph::Has/IsValid checks, pass post-
conditions — plus InferShape/InferMeta consistency enforcement).

A recorded ``Program`` (static/graph.py) is an op list over named
``Variable``s; rewrite passes (static/passes.py) mutate it in place, and
a buggy pass can silently produce a malformed block: an op reading a
variable no pass produces anymore, two ops claiming the same output name,
a fused op whose lowering computes a different shape than the recorded
metadata promises.  This module re-checks the invariants record-time
construction guarantees:

- **def-before-use / SSA** (V001/V002/V003): every ``var`` input must be
  a feed, a loop shadow, or the output of a PRECEDING op in the same
  block or an ancestor block; every name is produced at most once.
- **branch locality** (V004): a value produced inside a control-flow
  sub-block can only leave through the cond/while op's declared outputs.
- **dead ops** (V005) and **unfetchable fetches** (V006) when the fetch
  targets are known (``fetch_list``/``keep``).
- **shape/dtype re-inference** (V007/V008): re-run ``jax.eval_shape``
  per ``OpDesc`` — the same InferShape analog record_op used — and diff
  against the recorded output metadata.  A pass that swaps an op's
  ``fn`` but lies about the result shape is caught here before XLA
  compiles garbage (or worse, compiles fine and computes garbage).

Everything is duck-typed against the OpDesc/Block/Program protocol so
this module imports neither jax nor the static package at import time.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

__all__ = [
    "Diagnostic",
    "ProgramVerificationError",
    "verify_program",
    "ERROR",
    "WARNING",
    "INFO",
]

ERROR = "error"
WARNING = "warning"
INFO = "info"

# ops interpreted specially by _Interp — they have no re-inferable fn
_SPECIAL_OPS = ("backward", "cond", "while")

_SUB_BLOCK_KEYS = ("true_block", "false_block", "cond_block", "body_block")


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One verifier/hazard/lint finding."""

    code: str
    severity: str
    message: str
    where: str = ""

    def __str__(self):
        loc = f" [{self.where}]" if self.where else ""
        return f"{self.code} {self.severity.upper()}{loc}: {self.message}"


class ProgramVerificationError(RuntimeError):
    """Raised under ``strict=True`` when error-severity findings exist."""

    def __init__(self, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        lines = "\n".join(f"  {d}" for d in self.diagnostics)
        super().__init__(
            f"program verification failed with "
            f"{len(self.diagnostics)} finding(s):\n{lines}")


def _op_where(block, op_idx, op) -> str:
    return f"block {block.idx} op {op_idx} ({op.type})"


def _sub_blocks(op):
    for key in _SUB_BLOCK_KEYS:
        blk = op.extra.get(key) if op.extra else None
        if blk is not None:
            yield key, blk


def _branch_out_vars(op):
    """Variables a cond/while op's sub-blocks must have defined."""
    outs = []
    if not op.extra:
        return outs
    for key in ("true_out", "false_out", "body_out"):
        for o in op.extra.get(key) or []:
            if _is_variable(o):
                outs.append((key, o))
    p = op.extra.get("pred_out")
    if _is_variable(p):
        outs.append(("pred_out", p))
    return outs


def _is_variable(x) -> bool:
    # duck-typed: a symbolic Variable has a .block and a .name; eager
    # Tensors riding as consts have no .block
    return hasattr(x, "block") and hasattr(x, "name") and \
        getattr(x, "block", None) is not None


class _Checker:
    def __init__(self, program, fetch_list, reinfer: bool):
        self.program = program
        self.fetch_list = fetch_list
        self.reinfer = reinfer
        self.diags: List[Diagnostic] = []
        # name -> block idx that produced it (feeds map to their block)
        self.produced_in = {}
        self.producers = {}  # name -> (block_idx, op_idx) first producer
        self.consumed = set()

    def add(self, code, severity, message, where=""):
        self.diags.append(Diagnostic(code, severity, message, where))

    # -- visibility ------------------------------------------------------
    def _ancestors(self, block_idx: int):
        seen = set()
        while block_idx >= 0 and block_idx not in seen:
            seen.add(block_idx)
            block_idx = self.program.blocks[block_idx].parent_idx
        return seen

    # -- main walk -------------------------------------------------------
    def run(self) -> List[Diagnostic]:
        root = self.program.global_block()
        defined = set()
        for name, v in root.vars.items():
            if getattr(v, "is_data", False) or \
                    getattr(v, "persistable", False):
                defined.add(name)
                self.produced_in.setdefault(name, root.idx)
        self._check_block(root, defined)
        self._check_dead_and_fetches(root)
        return self.diags

    def _check_block(self, block, defined: set):
        visible_blocks = self._ancestors(block.idx)
        for op_idx, op in enumerate(block.ops):
            where = _op_where(block, op_idx, op)
            for kind, ref in op.inputs:
                if kind != "var":
                    continue
                name = getattr(ref, "name", None)
                self.consumed.add(name)
                if name in defined:
                    # defined — but was it defined in a visible block?
                    src = self.produced_in.get(name)
                    if src is not None and src not in visible_blocks:
                        self.add(
                            "V004", ERROR,
                            f"input '{name}' is local to sub-block {src} "
                            "and cannot be read from this block (branch-"
                            "local values leave only through the control-"
                            "flow op's outputs)", where)
                    continue
                if name in self.produced_in:
                    # produced, but in a block not visible from here —
                    # a branch-local value leaked past its sub-block
                    self.add(
                        "V004", ERROR,
                        f"input '{name}' is local to sub-block "
                        f"{self.produced_in[name]} and cannot be read "
                        "from this block (branch-local values leave "
                        "only through the control-flow op's outputs)",
                        where)
                elif self._registered_anywhere(name):
                    self.add(
                        "V002", ERROR,
                        f"input '{name}' is used before it is defined "
                        "(no preceding op produces it and it is not a "
                        "feed)", where)
                else:
                    self.add(
                        "V001", ERROR,
                        f"input references unknown variable '{name}' "
                        "(dangling reference: not registered in any "
                        "block of this program)", where)
            # control-flow sub-blocks see everything defined so far plus,
            # for while, the loop shadows bound by the interpreter
            if op.type in ("cond", "while"):
                inner = set(defined)
                for s in (op.extra.get("shadows") or []
                          if op.extra else []):
                    inner.add(s.name)
                    self.produced_in.setdefault(
                        s.name, getattr(s.block, "idx", block.idx))
                for _, blk in _sub_blocks(op):
                    # each branch sees the same pre-branch environment
                    self._check_block(blk, set(inner))
                self._check_branch_outputs(op, where)
            if op.type not in _SPECIAL_OPS and self.reinfer:
                self._reinfer_op(block, op_idx, op)
            for o in op.outputs:
                prev = self.producers.get(o.name)
                if prev is not None:
                    pb, pi = prev
                    self.add(
                        "V003", ERROR,
                        f"output '{o.name}' is produced twice (first at "
                        f"block {pb} op {pi}) — SSA discipline violated",
                        where)
                else:
                    self.producers[o.name] = (block.idx, op_idx)
                defined.add(o.name)
                self.produced_in[o.name] = block.idx

    def _check_branch_outputs(self, op, where):
        for key, o in _branch_out_vars(op):
            if o.name not in self.producers and \
                    o.name not in self.produced_in:
                self.add(
                    "V001", ERROR,
                    f"control-flow {key} references '{o.name}', which "
                    "no op produces", where)

    def _registered_anywhere(self, name) -> bool:
        return any(name in b.vars for b in self.program.blocks)

    # -- dead ops / fetches ---------------------------------------------
    def _check_dead_and_fetches(self, root):
        fetch_names = set()
        if self.fetch_list is not None:
            for ref in self.fetch_list:
                name = ref if isinstance(ref, str) else \
                    getattr(ref, "name", None)
                if name is not None:
                    fetch_names.add(name)
            for name in sorted(fetch_names):
                if name not in self.produced_in:
                    self.add(
                        "V006", ERROR,
                        f"fetch target '{name}' is neither produced by "
                        "any op nor a feed — a pass removed or renamed "
                        "its producer")
                elif self.produced_in[name] != root.idx:
                    self.add(
                        "V006", ERROR,
                        f"fetch target '{name}' is produced inside sub-"
                        f"block {self.produced_in[name]}; only global-"
                        "block values are fetchable")
        # branch outputs count as consumption of the sub-block terminals
        live = set(self.consumed) | fetch_names
        for op in _all_ops(self.program):
            for _, o in _branch_out_vars(op):
                live.add(o.name)
        if self.fetch_list is None:
            return
        for op_idx, op in enumerate(root.ops):
            if op.writeback or op.type in _SPECIAL_OPS:
                continue
            outs = list(op.outputs)
            if outs and all(
                    o.name not in live
                    and not getattr(o, "persistable", False)
                    for o in outs):
                self.add(
                    "V005", WARNING,
                    f"dead op: no output of "
                    f"{[o.name for o in outs]} is consumed, fetched, or "
                    "written back (eliminate_dead_ops would remove it)",
                    _op_where(root, op_idx, op))

    # -- shape/dtype re-inference ---------------------------------------
    def _reinfer_op(self, block, op_idx, op):
        if op.fn is None:
            return
        where = _op_where(block, op_idx, op)
        import jax

        specs, spec_pos, flat = [], [], []
        for i, (kind, ref) in enumerate(op.inputs):
            flat.append(ref)
            if kind == "var":
                v = getattr(ref, "_value", None)
                if v is None:
                    return
                specs.append(jax.ShapeDtypeStruct(tuple(v.shape), v.dtype))
                spec_pos.append(i)
            elif kind == "const":
                v = ref._value
                specs.append(jax.ShapeDtypeStruct(tuple(v.shape), v.dtype))
                spec_pos.append(i)
            elif kind == "dyn":
                import jax.numpy as jnp

                try:
                    v = jnp.asarray(ref())
                except Exception:  # noqa: BLE001 — provider needs runtime
                    return
                specs.append(jax.ShapeDtypeStruct(tuple(v.shape), v.dtype))
                spec_pos.append(i)

        from ..static.graph import _call_op_fn

        def shape_fn(*vals):
            return _call_op_fn(op.fn, flat, op.treedef, spec_pos, vals,
                               op.attrs)

        from ..ops import random as rnd

        prev = rnd.set_trace_key_provider(lambda: jax.random.PRNGKey(0))
        try:
            out_aval = jax.eval_shape(shape_fn, *specs)
        except Exception as e:  # noqa: BLE001 — surface, don't crash
            self.add("V009", WARNING,
                     f"shape re-inference failed: {type(e).__name__}: {e}",
                     where)
            return
        finally:
            rnd.set_trace_key_provider(prev)
        out_list = [out_aval] if op.single else list(out_aval)
        if len(out_list) != len(op.outputs):
            self.add(
                "V007", ERROR,
                f"op declares {len(op.outputs)} outputs but its fn "
                f"produces {len(out_list)}", where)
            return
        for o, inferred in zip(op.outputs, out_list):
            rec = o._value
            if tuple(rec.shape) != tuple(inferred.shape):
                self.add(
                    "V007", ERROR,
                    f"recorded shape {tuple(rec.shape)} of '{o.name}' "
                    f"disagrees with re-inferred {tuple(inferred.shape)} "
                    "(a pass rewired this op without updating metadata)",
                    where)
            if rec.dtype != inferred.dtype:
                self.add(
                    "V008", ERROR,
                    f"recorded dtype {rec.dtype} of '{o.name}' disagrees "
                    f"with re-inferred {inferred.dtype}", where)


def _all_ops(program):
    for b in program.blocks:
        for op in b.ops:
            yield op


def verify_program(program, fetch_list: Optional[Sequence[Any]] = None,
                   strict: bool = False,
                   reinfer: bool = True) -> List[Diagnostic]:
    """Check structural invariants of a recorded Program.

    ``fetch_list`` (Variables or names) enables dead-op (V005) and
    unfetchable-fetch (V006) detection — without it the verifier cannot
    tell a terminal result op from dead code, so those checks are
    skipped.  ``reinfer=False`` skips the per-op ``jax.eval_shape`` diff
    (V007/V008/V009) for cheap structural-only validation.

    Returns the diagnostics; with ``strict=True`` raises
    :class:`ProgramVerificationError` when any error-severity finding
    exists.
    """
    diags = _Checker(program, fetch_list, reinfer).run()
    if strict:
        errors = [d for d in diags if d.severity == ERROR]
        if errors:
            raise ProgramVerificationError(errors)
    return diags
