"""paddle_tpu.analysis — static checking for the TPU stack.

Three layers (reference: PaddlePaddle's ``framework/ir`` graph
validation, InferShape/InferMeta consistency enforcement, and
``tools/check_api_compatible.py``):

- :mod:`paddle_tpu.analysis.verifier` — structural Program verifier
  (def-before-use/SSA across sub-blocks, dangling Variable refs, dead
  ops, shape/dtype re-inference against ``jax.eval_shape``).  Runs
  automatically after every graph rewrite pass.
- :mod:`paddle_tpu.analysis.hazards` — TPU performance-hazard detector
  over recorded Programs and ``@to_static`` functions (scalar-capture
  recompiles, host syncs in traced regions, f64 upcasts, weak-type
  promotion leaks, zero-trip loop-var deviation, per-token host work
  in registered serving decode steps).
- :mod:`paddle_tpu.analysis.astlint` — repo AST lint (op-schema parity,
  inplace-alias pairing, jax-import boundaries, mutable defaults), also
  exposed as the ``tools/lint_tpu.py`` CLI and a ``lint`` CI stage.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

from .verifier import (ERROR, INFO, WARNING, Diagnostic,
                       ProgramVerificationError, verify_program)
from .hazards import (scan, scan_checkpoint_writes, scan_decode_step,
                      scan_decode_steps, scan_device_count_assumptions,
                      scan_function, scan_process_write_races, scan_program,
                      scan_static_function, scan_wall_clock_deadlines,
                      sort_diagnostics)
from . import astlint
from . import topology
from . import xray
from .xray import (ProgramReport, analyze, analyze_train_step,
                   audit_default_steps, check_sharding_readiness)
from .topology import (RankedLayout, Topology, format_recommendations)
from . import shardplan
from .shardplan import (Collective, PlanReport, PlanRequest,
                        audit_shardplan, plan_jaxpr, plan_step,
                        plan_train_step, recommend_layouts)
from . import fusionminer
from .fusionminer import (FusionCandidate, FusionReport, audit_fusion,
                          mine, mine_jaxpr)

__all__ = [
    "Diagnostic",
    "ProgramVerificationError",
    "verify_program",
    "scan",
    "scan_program",
    "scan_function",
    "scan_static_function",
    "scan_decode_step",
    "scan_decode_steps",
    "scan_checkpoint_writes",
    "scan_wall_clock_deadlines",
    "scan_device_count_assumptions",
    "scan_process_write_races",
    "sort_diagnostics",
    "set_pass_verification",
    "pass_verification",
    "verify_after_pass",
    "astlint",
    "xray",
    "ProgramReport",
    "analyze",
    "analyze_train_step",
    "audit_default_steps",
    "check_sharding_readiness",
    "shardplan",
    "topology",
    "Collective",
    "PlanReport",
    "PlanRequest",
    "RankedLayout",
    "Topology",
    "audit_shardplan",
    "fusionminer",
    "FusionCandidate",
    "FusionReport",
    "audit_fusion",
    "mine",
    "mine_jaxpr",
    "format_recommendations",
    "plan_jaxpr",
    "plan_step",
    "plan_train_step",
    "recommend_layouts",
    "ERROR",
    "WARNING",
    "INFO",
]

# Pass-guard policy.  Structural verification after every rewrite pass is
# cheap (metadata walk); re-inference is skipped there because passes
# legitimately replace fns with fused equivalents whose per-op shapes are
# re-checked by record-time eval_shape anyway.  ``strict`` escalates
# findings from stderr warnings to ProgramVerificationError.
_PASS_VERIFY = {"enabled": True, "strict": False}


def set_pass_verification(enabled: bool = True, strict: bool = False):
    """Configure the automatic verifier run after ``apply_pass`` /
    ``apply_build_strategy``.  Returns the previous policy."""
    prev = dict(_PASS_VERIFY)
    _PASS_VERIFY["enabled"] = bool(enabled)
    _PASS_VERIFY["strict"] = bool(strict)
    return prev


def pass_verification() -> dict:
    """Current pass-guard policy (copy)."""
    return dict(_PASS_VERIFY)


def verify_after_pass(program, pass_name: str,
                      fetch_list: Optional[Sequence[Any]] = None
                      ) -> List[Diagnostic]:
    """Guard hook called by ``static.passes`` after a pass rewrote ops.

    Honors :func:`set_pass_verification`; under the default non-strict
    policy, error findings are printed to stderr (a buggy pass should be
    loud even when the user never asked for verification), and under
    ``strict`` they raise :class:`ProgramVerificationError`.
    """
    if not _PASS_VERIFY["enabled"]:
        return []
    diags = verify_program(program, fetch_list=fetch_list,
                           strict=False, reinfer=False)
    errors = [d for d in diags if d.severity == ERROR]
    if errors and _PASS_VERIFY["strict"]:
        raise ProgramVerificationError(errors)
    if errors:
        import sys

        print(f"[paddle_tpu.analysis] pass '{pass_name}' left the "
              f"program malformed ({len(errors)} finding(s)):",
              file=sys.stderr)
        for d in errors:
            print(f"  {d}", file=sys.stderr)
    return diags
