"""Repo-specific AST lint for the paddle_tpu op surface and TPU discipline
(reference: tools/check_api_compatible.py as an API gate, plus the
codestyle hooks under tools/codestyle/).

Rules (all ERROR severity unless noted):

- **L001 op-schema-missing** — every public top-level function in an
  ``paddle_tpu/ops/`` submodule must have an ``op_schema.yaml`` entry.
- **L002 op-schema-signature** — the schema entry's parameter names must
  match the ``def`` (the runtime gate ``tests/test_op_schema.py`` pins
  exact default reprs; this static half catches drift without importing
  the package).
- **L003 inplace-unpaired** — ``op_schema.yaml`` ``inplace:`` variants
  and the live ``_INPLACE_ALIASES`` table in ``ops/__init__.py`` must
  stay paired in both directions (``add_`` ↔ ``add``).
- **L004 jax-import** — ``jax`` may be imported only in sanctioned
  modules (``core/``, ``ops/``, ``kernels/``, ``static/``,
  ``distributed/``): everything else goes through the public paddle_tpu
  surface so backend policy (precision, donation, sharding) stays in one
  layer.  Legacy numeric modules carry explicit file-level suppressions.
- **L005 mutable-default** — no mutable default arguments
  (``def f(x=[])``): shared-state bugs plus retrace hazards when the
  default rides a trace signature.
- **L006 dynamic-metric-name** — the metric NAME passed to a
  ``Counter(...)``/``Gauge(...)``/``Histogram(...)`` constructor or a
  ``.counter()``/``.gauge()``/``.histogram()`` registry factory must be
  a static string, not an f-string/``%``/``.format``/concatenation:
  per-value names are unbounded metric cardinality (one time series per
  request id).  Varying dimensions belong in LABELS, which the
  observability registry caps per metric.

Suppressions (documented in README):

- line-level:  ``some_code  # lint-tpu: disable=L004`` (comma-separate
  several codes, or ``disable=all``)
- file-level:  a comment line anywhere in the file reading
  ``# lint-tpu: disable-file=L004``

This module is deliberately self-contained (stdlib + yaml only, no
paddle_tpu imports) so ``tools/lint_tpu.py`` can load it by path and
lint the whole repo in milliseconds without pulling in jax.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Sequence, Set

__all__ = ["Finding", "RULES", "lint_file", "lint_paths", "main"]

ERROR = "error"
WARNING = "warning"


class Finding(NamedTuple):
    path: str
    line: int
    code: str
    severity: str
    message: str

    def __str__(self):
        return (f"{self.path}:{self.line}: {self.code} "
                f"[{self.severity.upper()}] {self.message}")


RULES: Dict[str, str] = {
    "L001": "public op function missing from op_schema.yaml",
    "L002": "op signature drifted from its op_schema.yaml entry",
    "L003": "inplace alias and schema 'inplace:' field out of sync",
    "L004": "jax imported outside sanctioned modules "
            "(core/, ops/, kernels/, static/, distributed/)",
    "L005": "mutable default argument",
    "L006": "metric name built from a formatted string at a "
            "Counter/Gauge/Histogram call site (unbounded cardinality)",
}

_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}

_SANCTIONED_ROOTS = ("core", "ops", "kernels", "static", "distributed")
_OPS_SUBMODULES = ("creation", "math", "manipulation", "logic", "linalg",
                   "search", "stat", "random", "einsum")

_SUPPRESS_RE = re.compile(
    r"#\s*lint-tpu:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<codes>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


# ---------------------------------------------------------------------------
# schema loading (yaml, no package import)
# ---------------------------------------------------------------------------

_SCHEMA_CACHE: Optional[dict] = None


def _schema_path() -> str:
    return os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "ops",
        "op_schema.yaml"))


def _load_schema() -> dict:
    """{op name: entry dict} from op_schema.yaml ({} if unreadable)."""
    global _SCHEMA_CACHE
    if _SCHEMA_CACHE is None:
        try:
            import yaml

            with open(_schema_path()) as f:
                raw = yaml.safe_load(f)
            _SCHEMA_CACHE = {e["op"]: e for e in raw["ops"]}
        except Exception:  # noqa: BLE001 — lint must not crash on it
            _SCHEMA_CACHE = {}
    return _SCHEMA_CACHE


def _sig_param_names(sig: str) -> Optional[List[str]]:
    """Ordered parameter names (with */** prefixes) from a canonical
    signature string like "(x, axis=None, *args, **kwargs)"."""
    try:
        tree = ast.parse(f"def _f{sig}: pass")
        args = tree.body[0].args
    except SyntaxError:
        return None
    return _arg_names(args)


def _arg_names(args: ast.arguments) -> List[str]:
    out = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    if args.vararg:
        out.append("*" + args.vararg.arg)
    out.extend(a.arg for a in args.kwonlyargs)
    if args.kwarg:
        out.append("**" + args.kwarg.arg)
    return out


# ---------------------------------------------------------------------------
# per-file analysis
# ---------------------------------------------------------------------------

def _package_relpath(path: str) -> Optional[str]:
    """Path relative to the innermost ``paddle_tpu`` package dir, or None
    when the file is not inside the package (tests, tools, ...)."""
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    if "paddle_tpu" not in parts:
        return None
    idx = len(parts) - 1 - parts[::-1].index("paddle_tpu")
    rel = parts[idx + 1:]
    return "/".join(rel) if rel else None


def _suppressions(src: str):
    """(file-level codes, {line: codes}) from lint-tpu comments."""
    file_codes: Set[str] = set()
    line_codes: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        codes = {c.strip().upper() for c in m.group("codes").split(",")
                 if c.strip()}
        if m.group("file"):
            file_codes |= codes
        else:
            line_codes.setdefault(lineno, set()).update(codes)
    return file_codes, line_codes


def _suppressed(code: str, lineno: int, file_codes, line_codes) -> bool:
    if "ALL" in file_codes or code in file_codes:
        return True
    at_line = line_codes.get(lineno, ())
    return "ALL" in at_line or code in at_line


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, relpath: Optional[str]):
        self.path = path
        self.relpath = relpath
        self.findings: List[Finding] = []
        root = relpath.split("/", 1)[0] if relpath else None
        self.sanctioned = (relpath is None
                           or root in _SANCTIONED_ROOTS
                           or root == "analysis")
        self.ops_submodule = None
        if relpath:
            m = re.fullmatch(r"ops/(\w+)\.py", relpath)
            if m and m.group(1) in _OPS_SUBMODULES:
                self.ops_submodule = m.group(1)
        self._depth = 0

    def add(self, node, code, message, severity=ERROR):
        self.findings.append(Finding(
            self.path, getattr(node, "lineno", 1), code, severity,
            message))

    # -- L004: jax imports ----------------------------------------------
    def visit_Import(self, node):
        if not self.sanctioned:
            for alias in node.names:
                if alias.name == "jax" or alias.name.startswith("jax."):
                    self.add(node, "L004",
                             f"import of '{alias.name}' outside "
                             "sanctioned modules " +
                             str(list(_SANCTIONED_ROOTS)))
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        if not self.sanctioned and (mod == "jax"
                                    or mod.startswith("jax.")):
            self.add(node, "L004",
                     f"import from '{mod}' outside sanctioned modules " +
                     str(list(_SANCTIONED_ROOTS)))
        self.generic_visit(node)

    # -- L005: mutable defaults -----------------------------------------
    def _check_defaults(self, node, args: ast.arguments):
        for default in list(args.defaults) + \
                [d for d in args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set")):
                self.add(default, "L005",
                         f"mutable default argument in "
                         f"'{getattr(node, 'name', '<lambda>')}' — "
                         "shared across calls and unhashable in trace "
                         "signatures; use None and construct inside")

    # -- L001/L002: op schema -------------------------------------------
    def visit_FunctionDef(self, node):
        self._check_defaults(node, node.args)
        if self.ops_submodule and self._depth == 0 and \
                not node.name.startswith("_"):
            self._check_op_schema(node)
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    def visit_AsyncFunctionDef(self, node):
        self.visit_FunctionDef(node)

    def visit_ClassDef(self, node):
        self._depth += 1  # methods are not module-level ops
        self.generic_visit(node)
        self._depth -= 1

    def visit_Lambda(self, node):
        self._check_defaults(node, node.args)
        self.generic_visit(node)

    # -- L006: dynamic metric names -------------------------------------
    @staticmethod
    def _is_dynamic_str(node) -> bool:
        """A string expression whose VALUE varies at runtime: f-string
        with interpolations, %-format off a literal, ``"...".format()``,
        or concatenation involving a string piece (fully-constant
        expressions don't count)."""
        d = _FileLinter._is_dynamic_str
        if isinstance(node, ast.JoinedStr):
            return any(isinstance(v, ast.FormattedValue)
                       for v in node.values)

        def is_str_const(n):
            return isinstance(n, ast.Constant) and isinstance(n.value, str)

        if isinstance(node, ast.BinOp) and \
                isinstance(node.op, (ast.Mod, ast.Add)):
            sides = (node.left, node.right)
            has_str = any(is_str_const(s) or d(s) for s in sides)
            all_const = all(is_str_const(s) for s in sides)
            return has_str and not all_const
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "format" and \
                is_str_const(node.func.value):
            return True
        return False

    def visit_Call(self, node):
        func = node.func
        is_metric = (isinstance(func, ast.Name)
                     and func.id in _METRIC_CTORS) or \
                    (isinstance(func, ast.Attribute)
                     and func.attr in (_METRIC_CTORS | _METRIC_FACTORIES))
        if is_metric:
            name_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            if name_arg is not None and self._is_dynamic_str(name_arg):
                what = func.id if isinstance(func, ast.Name) else func.attr
                self.add(node, "L006",
                         f"metric name passed to '{what}(...)' is built "
                         "from a formatted string — every distinct value "
                         "becomes its own time series (unbounded "
                         "cardinality); use a fixed name and put the "
                         "varying dimension in a label")
        self.generic_visit(node)

    def _check_op_schema(self, node):
        schema = _load_schema()
        if not schema:
            return
        entry = schema.get(node.name)
        if entry is None:
            self.add(node, "L001",
                     f"public op '{node.name}' in ops/"
                     f"{self.ops_submodule}.py has no op_schema.yaml "
                     "entry — run tools/gen_op_schema.py and commit "
                     "the diff")
            return
        if entry.get("module") != self.ops_submodule:
            return  # same name owned by another submodule entry
        declared = _sig_param_names(entry.get("signature", ""))
        actual = _arg_names(node.args)
        if declared is not None and declared != actual:
            self.add(node, "L002",
                     f"op '{node.name}' signature drifted from schema: "
                     f"declared params {declared}, actual {actual} — "
                     "regenerate with tools/gen_op_schema.py if "
                     "intentional")


def _lint_inplace_pairing(path: str, tree: ast.Module) -> List[Finding]:
    """L003 over ops/__init__.py: _INPLACE_ALIASES keys vs schema."""
    findings: List[Finding] = []
    schema = _load_schema()
    if not schema:
        return findings
    aliases = None
    alias_node = None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and \
                        tgt.id == "_INPLACE_ALIASES" and \
                        isinstance(node.value, ast.Dict):
                    aliases = {
                        k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
                    alias_node = node
    if aliases is None:
        return findings
    declared = {entry["inplace"]: name for name, entry in schema.items()
                if entry.get("inplace")}
    for inplace_name, base in sorted(declared.items()):
        if inplace_name not in aliases:
            findings.append(Finding(
                path, alias_node.lineno, "L003", ERROR,
                f"schema declares inplace variant '{inplace_name}' for "
                f"'{base}' but _INPLACE_ALIASES has no such entry"))
    for inplace_name in sorted(aliases):
        base = inplace_name[:-1]
        if base in schema and inplace_name not in declared:
            findings.append(Finding(
                path, alias_node.lineno, "L003", ERROR,
                f"_INPLACE_ALIASES pairs '{inplace_name}' with op "
                f"'{base}' but the schema entry lacks "
                f"'inplace: {inplace_name}' — regenerate "
                "op_schema.yaml"))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_file(path: str, src: Optional[str] = None) -> List[Finding]:
    if src is None:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError) as e:
            return [Finding(path, 1, "L000", ERROR,
                            f"unreadable: {e}")]
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, "L000", ERROR,
                        f"syntax error: {e.msg}")]
    relpath = _package_relpath(path)
    linter = _FileLinter(path, relpath)
    linter.visit(tree)
    findings = linter.findings
    if relpath == "ops/__init__.py":
        findings.extend(_lint_inplace_pairing(path, tree))
    file_codes, line_codes = _suppressions(src)
    return [f for f in findings
            if not _suppressed(f.code, f.line, file_codes, line_codes)]


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        findings.extend(
                            lint_file(os.path.join(dirpath, fn)))
        else:
            findings.extend(lint_file(path))
    # deterministic output: (path, line, code) regardless of os.walk's
    # directory order, so CI diffs and test assertions never flake (the
    # sort is stable — same-line findings keep rule-visit order)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="paddle_tpu repo lint (op schema, jax-import "
        "boundaries, mutable defaults)")
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--warnings-as-errors", action="store_true")
    args = parser.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}: {desc}")
        return 0
    if not args.paths:
        parser.error("no paths given (try: python tools/lint_tpu.py "
                     "paddle_tpu/)")
    findings = lint_paths(args.paths)
    for f in findings:
        print(f)
    errors = [f for f in findings
              if f.severity == ERROR
              or (args.warnings_as_errors and f.severity == WARNING)]
    n_files = sum(len(list(_iter_py(p))) if os.path.isdir(p) else 1
                  for p in args.paths)
    print(f"lint-tpu: {n_files} files, {len(findings)} finding(s), "
          f"{len(errors)} error(s)")
    return 1 if errors else 0


def _iter_py(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)
