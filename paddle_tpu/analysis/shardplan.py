"""Static SPMD plan analyzer: sharding propagation, per-chip memory,
and communication cost from the jaxpr.

PR 6's X-ray answers "what does this program cost on ONE chip"; this
module answers "what does it cost on a MESH" — before any mesh exists.
Given a traced step (``jit.StaticFunction.trace_jaxpr`` or
``jax.make_jaxpr``), an **abstract mesh** (named axis sizes, no real
devices — the whole analysis runs on CPU tier-1), and a
:class:`~paddle_tpu.distributed.sharding.SpecLayout`, it propagates
shardings through the jaxpr the way GSPMD's partitioner would
(dot_general/conv from dimension numbers, elementwise union rules,
reshape split/merge, transpose permutation, recursion through
pjit/scan/while/cond like the cost model) and emits a
:class:`PlanReport`:

- **per-chip sharded peak HBM** — the xray liveness pass re-run with a
  shard-aware ``var_bytes`` callback that divides each buffer by its
  shard count, gated by ``hbm_budget_bytes`` *per chip* (H110 ERROR).
- **collective inventory** — every implied all-reduce / all-gather /
  reduce-scatter / all-to-all with ring-formula bytes on the wire
  (all-reduce moves ``2·S·(n-1)/n`` per chip, the others ``S·(n-1)/n``)
  and estimated time against the chip's ICI profile
  (:data:`~paddle_tpu.analysis.xray.CHIPS`).
- **diagnostics** — S205 resharding hotspot (a producer/consumer spec
  conflict forcing an *unplanned* gather above a byte threshold, ERROR),
  S206 fully-replicated large parameter (WARNING — HBM burned on every
  chip), S207 collective-bound step (estimated comm time exceeds the
  roofline compute time, ERROR), S208 batch dim not sharded on the
  ``data`` axis (WARNING — chunked prefill legitimately runs batch=1).

**Planned vs unplanned.**  A collective the layout *implies* is
planned: a sharded contraction ends in an all-reduce (row-parallel
matmul, data-parallel grad sync), a one-sided sharded contraction
all-gathers the sharded operand (the ZeRO-3/FSDP resolution), a lookup
into a vocab-sharded embedding lowers to masked-gather + all-reduce.
Unplanned collectives come from spec *conflicts* — the same mesh axis
claimed by two output dims, or an elementwise op whose operands
disagree — and are what S205 reports: they mean the layout fights
itself on that edge.

The propagation is a single forward pass (no GSPMD fix-point): loop
carries keep their entry spec, and unknown primitives inherit from a
same-shaped operand or fall back to replicated without inventing
collectives.  That makes the analysis conservative in the safe
direction — it can miss a resharding XLA would insert, but a *clean*
report means the layout is self-consistent on every edge this pass
understands.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .topology import Topology, format_recommendations, rank_layouts
from .verifier import ERROR, WARNING, Diagnostic
from .xray import (CHIPS, ChipProfile, _aval_bytes, _collect_costs,
                   _peak_live_by_dtype, _peak_live_bytes, _var_bytes,
                   estimate_collective_time, estimate_compute_time)

__all__ = [
    "Collective",
    "MoEStatics",
    "PlanReport",
    "PlanRequest",
    "Topology",
    "audit_shardplan",
    "export_plan_gauges",
    "plan_jaxpr",
    "plan_step",
    "plan_train_step",
    "recommend_layouts",
]

#: step kinds where a request round-trips the step on the critical
#: path — any DCN-crossing collective inside one is an S213 ERROR
LATENCY_CRITICAL_STEP_KINDS = frozenset(
    {"decode", "beam_decode", "paged_decode", "prefill",
     "chunked_prefill", "sampled_decode", "draft_propose",
     "spec_verify"})

#: S213 noise floor: a DCN edge must move at least this many wire
#: bytes per step to be flagged — scalar-sized control reduces (the
#: conservative gather rule prices an aligned per-shard lookup as an
#: 8-byte all_reduce) are priced into the totals but not latency-gated
_S213_FLOOR_BYTES = 256


# ---------------------------------------------------------------------------
# spec algebra: a ShardSpec is a per-dimension tuple of mesh-axis names
# ---------------------------------------------------------------------------

ShardSpec = Tuple[Tuple[str, ...], ...]


def _rep(rank: int) -> ShardSpec:
    return ((),) * rank


def _rank(v) -> int:
    return len(getattr(v.aval, "shape", ()) or ())


def _normalize_spec(spec, rank: int) -> ShardSpec:
    """PartitionSpec / tuple / None → canonical per-dim axis tuples,
    padded with replicated entries to ``rank``."""
    if spec is None:
        return _rep(rank)
    entries: List[Tuple[str, ...]] = []
    for e in tuple(spec)[:rank]:
        if e is None:
            entries.append(())
        elif isinstance(e, (tuple, list)):
            entries.append(tuple(str(a) for a in e))
        else:
            entries.append((str(e),))
    while len(entries) < rank:
        entries.append(())
    return tuple(entries)


def _axes_product(axes: Sequence[str], mesh: Dict[str, int]) -> int:
    n = 1
    for a in axes:
        n *= int(mesh.get(a, 1))
    return n


def _shard_count(spec: ShardSpec, mesh: Dict[str, int]) -> int:
    n = 1
    for entry in spec:
        n *= _axes_product(entry, mesh)
    return max(1, n)


def _spec_str(spec: ShardSpec) -> str:
    def one(entry):
        if not entry:
            return "·"
        return "+".join(entry)
    return "(" + ", ".join(one(e) for e in spec) + ")"


# primitives that carry an axis_name param but move no tensor bytes —
# they must not trip the S210 unpriced-collective detector
_AXIS_NAME_FREE = {"axis_index", "axis_size", "pvary"}


# ---------------------------------------------------------------------------
# report dataclasses
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Collective:
    """One implied collective.  ``payload_bytes`` is the logical tensor
    size being communicated (already divided by its shard count over
    the *other* axes); ``bytes_moved`` is per-chip wire traffic from the
    ring formula; ``count`` is the static trip multiplier (scan)."""

    kind: str                 # all_reduce | all_gather | reduce_scatter | all_to_all
    axes: Tuple[str, ...]
    payload_bytes: int
    bytes_moved: int
    time_s: float
    planned: bool
    primitive: str
    count: float = 1.0
    # link level the bytes ride: "ici" (intra-host, the only level a
    # flat single-host plan has) or "dcn" (cross-host phase of a
    # topology-decomposed collective)
    level: str = "ici"

    @property
    def total_bytes(self) -> float:
        return self.bytes_moved * self.count

    @property
    def total_time_s(self) -> float:
        return self.time_s * self.count


@dataclasses.dataclass(frozen=True)
class MoEStatics:
    """Static description of one capacity-padded MoE dispatch (GShard
    style ``[E, C, M]`` buffers).  Lets the planner (a) price the expert
    exchange as an all_to_all sized from the padded payload instead of a
    worst-case all-reduce and (b) statically check capacity overflow
    (S211: ``tokens·top_k > experts·capacity`` drops routed tokens)."""

    experts: int               # E
    capacity: int              # C slots per expert
    top_k: int                 # routing choices per token
    tokens: int                # tokens routed per step (batch · seq)
    capacity_factor: float = 1.0
    expert_axis: str = "expert"


@dataclasses.dataclass
class PlanReport:
    """Static mesh-execution plan for one traced step."""

    name: str
    chip: ChipProfile
    mesh: Dict[str, int]
    n_chips: int
    per_chip_peak_hbm_bytes: int
    collectives: List[Collective]
    flops: float               # whole-program, all chips
    bytes: float               # whole-program HBM bytes, all chips
    diagnostics: List[Diagnostic]
    param_specs: Dict[str, str]
    hbm_budget_bytes: Optional[int] = None
    # dtype -> per-chip bytes held at the liveness peak (sums to
    # per_chip_peak_hbm_bytes); the dtype-aware gauge for int8/fp8 KV
    per_chip_peak_hbm_by_dtype: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    # multi-host pricing context.  When a Topology is set,
    # ``collectives`` holds the hierarchically decomposed per-link
    # phases and ``flat_collectives`` keeps the raw single-level
    # inventory the propagation produced (what the layout recommender
    # reprices under other assignments); without one the two lists are
    # the same object.
    topology: Optional[Topology] = None
    flat_collectives: List[Collective] = dataclasses.field(
        default_factory=list)
    step_kind: Optional[str] = None

    @property
    def comm_bytes(self) -> float:
        return sum(c.total_bytes for c in self.collectives)

    @property
    def comm_time_s(self) -> float:
        return sum(c.total_time_s for c in self.collectives)

    @property
    def ici_comm_bytes(self) -> float:
        return sum(c.total_bytes for c in self.collectives
                   if c.level != "dcn")

    @property
    def dcn_comm_bytes(self) -> float:
        return sum(c.total_bytes for c in self.collectives
                   if c.level == "dcn")

    @property
    def ici_comm_time_s(self) -> float:
        return sum(c.total_time_s for c in self.collectives
                   if c.level != "dcn")

    @property
    def dcn_comm_time_s(self) -> float:
        return sum(c.total_time_s for c in self.collectives
                   if c.level == "dcn")

    @property
    def chips_per_host_count(self) -> int:
        if self.topology is not None:
            return self.topology.chips_per_host_count
        return max(1, self.n_chips)   # single host holds the mesh

    @property
    def per_host_peak_hbm_bytes(self) -> int:
        """HBM the busiest host must hold: per-chip peak × chips on
        one host (every chip of a host peaks in the same SPMD step)."""
        return self.per_chip_peak_hbm_bytes * self.chips_per_host_count

    @property
    def dcn_bytes_per_host(self) -> float:
        """DCN ingress+egress through one host's NIC per step — every
        resident chip's DCN wire bytes funnel through the host."""
        return self.dcn_comm_bytes * self.chips_per_host_count

    def to_json(self) -> Dict[str, Any]:
        """Machine-readable plan for ``lint_tpu --shardplan --json`` —
        CI diffs these across PRs instead of grepping the text table."""
        topo = self.topology
        return {
            "name": self.name,
            "step_kind": self.step_kind,
            "chip": self.chip.name,
            "mesh": dict(self.mesh),
            "n_chips": int(self.n_chips),
            "hosts": int(topo.hosts) if topo else 1,
            "chips_per_host": (list(topo.chips_per_host) if topo
                               else [max(1, self.n_chips)]),
            "axis_levels": ({a: topo.level_of(a, self.mesh)
                             for a in self.mesh} if topo else
                            {a: "ici" for a in self.mesh}),
            "per_chip_peak_hbm_bytes": int(self.per_chip_peak_hbm_bytes),
            "per_host_peak_hbm_bytes": int(self.per_host_peak_hbm_bytes),
            "per_chip_peak_hbm_by_dtype": {
                k: int(v)
                for k, v in sorted(self.per_chip_peak_hbm_by_dtype.items())},
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "wire_bytes": {"ici": int(self.ici_comm_bytes),
                           "dcn": int(self.dcn_comm_bytes)},
            "comm_time_s": {"ici": self.ici_comm_time_s,
                            "dcn": self.dcn_comm_time_s},
            "dcn_bytes_per_host": int(self.dcn_bytes_per_host),
            "compute_time_s": self.compute_time_s,
            "unplanned_collectives": sum(
                1 for c in self.collectives if not c.planned),
            "collectives": [
                {"kind": c.kind, "axes": list(c.axes), "level": c.level,
                 "payload_bytes": int(c.payload_bytes),
                 "bytes_moved": int(c.bytes_moved), "count": c.count,
                 "time_s": c.time_s, "planned": c.planned,
                 "primitive": c.primitive}
                for c in self.collectives],
            "diagnostics": [
                {"code": d.code, "severity": d.severity,
                 "message": d.message, "where": d.where}
                for d in self.diagnostics],
            "param_specs": dict(self.param_specs),
        }

    @property
    def compute_time_s(self) -> float:
        """Per-chip roofline time: the program's cost divided over the
        mesh, against the same formula xray's summary uses."""
        n = max(1, self.n_chips)
        return estimate_compute_time(self.flops / n, self.bytes / n,
                                     self.chip)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    def table(self, top: int = 12) -> str:
        """Collective inventory: kind, mesh axes, wire KiB/chip, µs,
        planned-or-conflict, producing primitive."""
        rows = [f"{'collective':<16}{'axes':<14}{'link':<6}"
                f"{'KiB/chip':>10}{'µs':>8}  plan  primitive"]
        ordered = sorted(self.collectives,
                         key=lambda c: (-c.total_bytes, c.kind, c.primitive))
        for c in ordered[:top]:
            rows.append(
                f"{c.kind:<16}{'×'.join(c.axes):<14}{c.level:<6}"
                f"{c.total_bytes / 1024:>10.2f}{c.total_time_s * 1e6:>8.2f}"
                f"  {'yes' if c.planned else 'NO':<4}  {c.primitive}")
        return "\n".join(rows)

    def summary(self) -> str:
        budget = (f" / budget {self.hbm_budget_bytes / 2**30:.2f} GiB"
                  if self.hbm_budget_bytes else "")
        mesh = ",".join(f"{k}={v}" for k, v in self.mesh.items())
        unplanned = sum(1 for c in self.collectives if not c.planned)
        if self.topology is not None:
            topo = (f" [{self.topology.hosts} host(s) × "
                    f"{self.chips_per_host_count} chips]")
            comm = (f"comm {self.comm_time_s * 1e6:.1f} µs "
                    f"(ICI {self.ici_comm_time_s * 1e6:.1f} + "
                    f"DCN {self.dcn_comm_time_s * 1e6:.1f})")
            host_hbm = (f", per-host peak HBM "
                        f"{self.per_host_peak_hbm_bytes / 2**20:.2f} MiB"
                        f", DCN {self.dcn_bytes_per_host / 2**20:.3f} "
                        "MiB/host/step")
        else:
            topo = ""
            comm = f"comm {self.comm_time_s * 1e6:.1f} µs"
            host_hbm = ""
        return (f"[shardplan] {self.name} on ({mesh}){topo} "
                f"@ {self.chip.name}: per-chip peak HBM "
                f"{self.per_chip_peak_hbm_bytes / 2**20:.2f} MiB{budget}, "
                f"{len(self.collectives)} collective(s) "
                f"({unplanned} unplanned, "
                f"{self.comm_bytes / 2**20:.3f} MiB on wire), "
                f"{comm} vs compute "
                f"{self.compute_time_s * 1e6:.1f} µs{host_hbm}, "
                f"{len(self.diagnostics)} diagnostic(s)")


@dataclasses.dataclass
class PlanRequest:
    """Opt-in config for ``Model.fit(shardplan=...)`` /
    ``ServingConfig.shardplan`` and the CLI — everything
    :func:`plan_train_step` / :func:`plan_step` need beyond the trace."""

    mesh: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {"data": 2, "fsdp": 2, "tp": 2})
    layout: Any = None            # SpecLayout; None → default
    chip: str = "cpu"
    hbm_budget_bytes: Optional[int] = None
    s205_bytes: int = 1 << 20     # unplanned-gather ERROR threshold
    s206_bytes: int = 8 << 20     # replicated-param WARNING threshold
    raise_on_error: bool = True
    moe: Optional[MoEStatics] = None  # set for MoE steps (S211 + a2a pricing)
    # multi-host topology: when set, collectives over host-spanning
    # axes decompose into ICI/DCN phases and per-host budgets apply
    topology: Optional[Topology] = None

    def resolved_layout(self):
        if self.layout is not None:
            return self.layout
        from ..distributed.sharding import SpecLayout

        return SpecLayout()


# ---------------------------------------------------------------------------
# the propagator
# ---------------------------------------------------------------------------

class _Planner:
    """Single forward sharding-propagation pass over a (nested) jaxpr.

    ``env`` maps every visited jaxpr Var to its ShardSpec — including
    vars of inner jaxprs, so the shard-aware liveness callback can
    resolve any var the peak-HBM walk touches."""

    def __init__(self, mesh: Dict[str, int], chip: ChipProfile,
                 moe: Optional[MoEStatics] = None):
        self.mesh = dict(mesh)
        self.chip = chip
        self.moe = moe
        self.env: Dict[Any, ShardSpec] = {}
        self.collectives: List[Collective] = []
        # (primitive, axes) pairs that carried an axis_name but have no
        # pricing rule — the S210 silent-blind-spot inventory
        self.unknown_collectives: List[Tuple[str, Tuple[str, ...]]] = []

    # -- env ---------------------------------------------------------------

    def spec_of(self, v) -> ShardSpec:
        if isinstance(v, jax.core.Literal):
            return _rep(_rank(v))
        return self.env.get(v, _rep(_rank(v)))

    def set_spec(self, v, spec: ShardSpec):
        if isinstance(v, jax.core.Literal):
            return
        self.env[v] = self._drop_indivisible(v, spec)

    def _drop_indivisible(self, v, spec: ShardSpec) -> ShardSpec:
        """A dim not divisible by its axis product cannot actually be
        sharded — treat it as replicated here (S204 complains at the
        layout level)."""
        shape = getattr(v.aval, "shape", ()) or ()
        out = []
        for dim, entry in enumerate(spec):
            n = _axes_product(entry, self.mesh)
            if n > 1 and dim < len(shape) and int(shape[dim]) % n != 0:
                out.append(())
            else:
                out.append(entry)
        return tuple(out)

    # -- collective emission -----------------------------------------------

    def emit(self, kind: str, axes: Sequence[str], payload: float,
             planned: bool, primitive: str, mul: float,
             factor: Optional[float] = None):
        axes = tuple(a for a in axes if self.mesh.get(a, 1) > 1)
        n = _axes_product(axes, self.mesh)
        if n <= 1 or payload <= 0:
            return
        if factor is None:
            factor = (2.0 * (n - 1) / n if kind == "all_reduce"
                      else (n - 1) / n)
        moved = int(payload * factor)
        self.collectives.append(Collective(
            kind=kind, axes=axes, payload_bytes=int(payload),
            bytes_moved=moved,
            time_s=estimate_collective_time(moved, self.chip),
            planned=planned, primitive=primitive, count=mul))

    def _dedupe(self, spec: ShardSpec, used: set, out_bytes: float,
                primitive: str, mul: float, planned: bool = False
                ) -> ShardSpec:
        """Drop axes already claimed elsewhere in the output; every drop
        of a real (>1) axis means the value must be gathered along it."""
        result: List[Tuple[str, ...]] = []
        for entry in spec:
            kept = []
            for a in entry:
                if a in used:
                    if self.mesh.get(a, 1) > 1:
                        self.emit("all_gather", (a,),
                                  out_bytes / _axes_product([a], self.mesh),
                                  planned, primitive, mul)
                else:
                    used.add(a)
                    kept.append(a)
            result.append(tuple(kept))
        return tuple(result)

    # -- walk --------------------------------------------------------------

    def run(self, jaxpr, mul: float = 1.0):
        for eqn in jaxpr.eqns:
            self._eqn(eqn, mul)

    def _eqn(self, eqn, mul: float):
        name = eqn.primitive.name
        handler = _RULES.get(name)
        if handler is not None:
            handler(self, eqn, mul)
        elif name == "pallas_call":
            # a priced LEAF, not a call: its params carry a "jaxpr" (the
            # per-block kernel body), but walking that would misread
            # one grid cell as the whole op — and its internal grid axes
            # must never read as unknown collectives (S210).  The fused
            # serving kernels run unsharded (models/llama.py falls back
            # to the gather path under a live mesh), so outputs
            # replicate and no wire traffic is emitted.
            self._default_specs_only(eqn)
        elif name in ("cond", "while", "scan", "pjit") or \
                "jaxpr" in eqn.params or "call_jaxpr" in eqn.params \
                or "fun_jaxpr" in eqn.params:
            self._call_like(eqn, mul)
        else:
            if name not in _AXIS_NAME_FREE and (
                    "axis_name" in eqn.params
                    or "axis_index_groups" in eqn.params):
                # a collective-looking primitive the planner cannot
                # price — record it so S210 surfaces the blind spot
                axes = eqn.params.get("axis_name", ())
                if isinstance(axes, str):
                    axes = (axes,)
                axes = tuple(str(a) for a in (axes or ()))
                if not axes or _axes_product(axes, self.mesh) > 1:
                    self.unknown_collectives.append((name, axes))
            self._default(eqn, mul)

    # -- generic rules -----------------------------------------------------

    def _default(self, eqn, mul: float):
        """Elementwise/unknown: per-dim union across broadcast-compatible
        operands (right-aligned; size-1 dims contribute nothing);
        disagreeing operands lose their axes (unplanned gather);
        unknown shapes replicate without inventing traffic."""
        for out in eqn.outvars:
            out_shape = tuple(getattr(out.aval, "shape", ()) or ())
            rank = len(out_shape)
            merged: List[Tuple[str, ...]] = [()] * rank
            conflict_axes: set = set()
            for v in eqn.invars:
                if isinstance(v, jax.core.Literal):
                    continue
                v_shape = tuple(getattr(v.aval, "shape", None) or ())
                off = rank - len(v_shape)
                if off < 0 or any(
                        s != out_shape[off + i] and s != 1
                        for i, s in enumerate(v_shape)):
                    continue
                spec = self.spec_of(v)
                for i, s in enumerate(v_shape):
                    d = off + i
                    if s != out_shape[d] or not spec[i]:
                        continue
                    if not merged[d]:
                        merged[d] = spec[i]
                    elif merged[d] != spec[i]:
                        conflict_axes.update(set(spec[i]) - set(merged[d]))
            for a in sorted(conflict_axes):
                self.emit("all_gather", (a,),
                          _aval_bytes(out.aval)
                          / _axes_product([a], self.mesh),
                          False, eqn.primitive.name, mul)
            used: set = set()
            final = self._dedupe(tuple(merged), used,
                                 _aval_bytes(out.aval),
                                 eqn.primitive.name, mul)
            self.set_spec(out, final)

    def _default_specs_only(self, eqn):
        """Replicated outputs, zero emitted traffic — for opaque priced
        leaves (pallas_call) whose operands the planner must not try to
        reshard through broadcast rules."""
        for out in eqn.outvars:
            rank = len(tuple(getattr(out.aval, "shape", ()) or ()))
            self.set_spec(out, _rep(rank))

    def _match_specs(self, outer_vars, inner_vars, outer_to_inner: bool):
        """Shape-aware pairing for call-like eqns: equal shapes copy the
        spec; a rank-1 difference with a matching tail is scan's
        stacked/per-iteration relationship (strip or prepend the leading
        dim); anything else replicates."""
        for ov, iv in zip(outer_vars, inner_vars):
            src, dst = (ov, iv) if outer_to_inner else (iv, ov)
            if isinstance(dst, jax.core.Literal):
                continue
            s_shape = tuple(getattr(src.aval, "shape", ()) or ())
            d_shape = tuple(getattr(dst.aval, "shape", ()) or ())
            spec = self.spec_of(src)
            if s_shape == d_shape:
                self.set_spec(dst, spec)
            elif len(s_shape) == len(d_shape) + 1 and s_shape[1:] == d_shape:
                self.set_spec(dst, spec[1:])
            elif len(d_shape) == len(s_shape) + 1 and d_shape[1:] == s_shape:
                self.set_spec(dst, ((),) + spec)
            else:
                self.set_spec(dst, _rep(len(d_shape)))

    def _call_like(self, eqn, mul: float):
        name = eqn.primitive.name
        params = eqn.params
        if name == "cond":
            branches = params["branches"]
            ops = eqn.invars[1:]
            # propagate every branch (liveness needs the env), but only
            # keep the most expensive branch's collectives — branches
            # are exclusive, same policy as the cost walk
            base = len(self.collectives)
            best: List[Collective] = []
            best_cost = -1.0
            for b in branches:
                inner = b.jaxpr
                self._match_specs(ops, inner.invars, True)
                self.run(inner, mul)
                mine = self.collectives[base:]
                del self.collectives[base:]
                cost = sum(c.total_bytes for c in mine)
                if cost > best_cost:
                    best, best_cost = mine, cost
                    self._match_specs(eqn.outvars, inner.outvars, False)
            self.collectives.extend(best)
            return
        if name == "while":
            cn = int(params.get("cond_nconsts", 0))
            bn = int(params.get("body_nconsts", 0))
            cond_j = params["cond_jaxpr"].jaxpr
            body_j = params["body_jaxpr"].jaxpr
            carry = eqn.invars[cn + bn:]
            self._match_specs(eqn.invars[:cn] + carry, cond_j.invars, True)
            self._match_specs(eqn.invars[cn:cn + bn] + carry,
                              body_j.invars, True)
            self.run(cond_j, mul)
            self.run(body_j, mul)
            self._match_specs(eqn.outvars, body_j.outvars, False)
            return
        if name == "scan":
            inner = params["jaxpr"].jaxpr
            trips = float(params.get("length", 1))
            self._match_specs(eqn.invars, inner.invars, True)
            self.run(inner, mul * trips)
            self._match_specs(eqn.outvars, inner.outvars, False)
            return
        # custom_vjp_call_jaxpr keeps its primal body under fun_jaxpr —
        # recursing through it makes hand-differentiated kernels
        # (moe_dispatch/combine) transparent instead of opaque leaves
        inner = params.get("jaxpr",
                           params.get("call_jaxpr",
                                      params.get("fun_jaxpr")))
        inner = getattr(inner, "jaxpr", inner)
        self._match_specs(eqn.invars, inner.invars, True)
        self.run(inner, mul)
        self._match_specs(eqn.outvars, inner.outvars, False)


# ---------------------------------------------------------------------------
# primitive-specific propagation rules
# ---------------------------------------------------------------------------

def _rule_dot_general(pl: _Planner, eqn, mul: float):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0], eqn.invars[1]
    ls, rs = pl.spec_of(lhs), pl.spec_of(rhs)
    out = eqn.outvars[0]
    out_bytes = _aval_bytes(out.aval)

    # contraction: axes sharded on BOTH sides → partial sums, one
    # planned all-reduce of the (already-assembled) output; axes on one
    # side only → planned all-gather of that operand (FSDP resolution)
    reduce_axes: List[str] = []
    for li, ri in zip(lc, rc):
        both = set(ls[li]) & set(rs[ri])
        reduce_axes.extend(sorted(both))
        for side_spec, side_var, dim in ((ls, lhs, li), (rs, rhs, ri)):
            only = set(side_spec[dim]) - both
            for a in sorted(only):
                payload = (_aval_bytes(side_var.aval)
                           / _shard_count(pl.spec_of(side_var), pl.mesh)
                           * _axes_product([a], pl.mesh))
                pl.emit("all_gather", (a,), payload, True,
                        "dot_general", mul)

    # output dims: batch, then lhs free, then rhs free
    used: set = set(reduce_axes)
    out_spec: List[Tuple[str, ...]] = []
    for li, ri in zip(lb, rb):
        axes = tuple(ls[li]) if ls[li] else tuple(rs[ri])
        if ls[li] and rs[ri] and set(ls[li]) != set(rs[ri]):
            for a in sorted(set(rs[ri]) - set(ls[li])):
                pl.emit("all_gather", (a,),
                        out_bytes / _axes_product([a], pl.mesh),
                        False, "dot_general", mul)
        out_spec.append(axes)
    for i in range(len(ls)):
        if i not in tuple(lc) + tuple(lb):
            out_spec.append(tuple(ls[i]))
    for i in range(len(rs)):
        if i not in tuple(rc) + tuple(rb):
            out_spec.append(tuple(rs[i]))
    final = pl._dedupe(tuple(out_spec), used, out_bytes, "dot_general", mul)
    out_shape = tuple(getattr(out.aval, "shape", ()) or ())
    moe = pl.moe
    # GShard MoE dispatch: a token-sharded contraction assembling the
    # capacity-padded [E, C, M] buffer that the expert axis consumes.
    # GSPMD lowers that exchange to an all_to_all over 'expert' (each
    # chip keeps only its experts' slots) plus the token-axis reduction
    # of the surviving local slice — not an all-reduce of the full
    # padded buffer on every chip.
    is_moe_dispatch = (
        moe is not None and reduce_axes and len(out_shape) >= 2
        and int(out_shape[0]) == int(moe.experts)
        and int(out_shape[1]) == int(moe.capacity)
        and pl.mesh.get(moe.expert_axis, 1) > 1
        and moe.expert_axis not in {a for e in final for a in e})
    if is_moe_dispatch and not final[0]:
        final = ((moe.expert_axis,),) + final[1:]
    pl.set_spec(out, final)
    if reduce_axes:
        if is_moe_dispatch:
            e_ax = moe.expert_axis
            e_n = _axes_product([e_ax], pl.mesh)
            payload = out_bytes / _shard_count(final[1:], pl.mesh)
            pl.emit("all_to_all", (e_ax,), payload, True,
                    "dot_general(moe_dispatch)", mul)
            rest = tuple(a for a in sorted(set(reduce_axes)) if a != e_ax)
            if rest:
                pl.emit("all_reduce", rest, payload / e_n, True,
                        "dot_general(moe_dispatch)", mul)
        else:
            payload = out_bytes / _shard_count(final, pl.mesh)
            pl.emit("all_reduce", tuple(sorted(set(reduce_axes))), payload,
                    True, "dot_general", mul)


def _rule_transpose(pl: _Planner, eqn, mul: float):
    perm = eqn.params["permutation"]
    spec = pl.spec_of(eqn.invars[0])
    pl.set_spec(eqn.outvars[0], tuple(spec[p] for p in perm))


def _rule_broadcast_in_dim(pl: _Planner, eqn, mul: float):
    bdims = eqn.params["broadcast_dimensions"]
    in_v, out = eqn.invars[0], eqn.outvars[0]
    spec = pl.spec_of(in_v)
    in_shape = tuple(getattr(in_v.aval, "shape", ()) or ())
    out_shape = tuple(out.aval.shape)
    out_spec = [()] * len(out_shape)
    for i, j in enumerate(bdims):
        if i < len(in_shape) and in_shape[i] == out_shape[j]:
            out_spec[j] = spec[i]
    pl.set_spec(out, tuple(out_spec))


def _reshape_groups(src: Sequence[int], dst: Sequence[int]):
    """Pair contiguous runs of src/dst dims with equal element products.
    Yields (src_dims, dst_dims) groups, or None if the factorization
    doesn't line up (fallback: drop all sharding)."""
    groups = []
    i = j = 0
    while i < len(src) or j < len(dst):
        si, sj = i, j
        pi = pj = 1
        if i < len(src):
            pi = src[i]
            i += 1
        if j < len(dst):
            pj = dst[j]
            j += 1
        while pi != pj:
            if pi < pj:
                if i >= len(src):
                    return None
                pi *= src[i]
                i += 1
            else:
                if j >= len(dst):
                    return None
                pj *= dst[j]
                j += 1
        groups.append((list(range(si, i)), list(range(sj, j))))
    return groups


def _rule_reshape(pl: _Planner, eqn, mul: float):
    in_v, out = eqn.invars[0], eqn.outvars[0]
    spec = pl.spec_of(in_v)
    src = [int(s) for s in (getattr(in_v.aval, "shape", ()) or ())]
    dst = [int(s) for s in out.aval.shape]
    groups = _reshape_groups(src, dst)
    out_spec: List[Tuple[str, ...]] = [()] * len(dst)
    gathered: List[str] = []
    if groups is None:
        gathered = [a for e in spec for a in e]
    else:
        for sdims, ddims in groups:
            sharded = [(d, spec[d]) for d in sdims if spec[d]]
            if not sharded:
                continue
            # sharding survives a split/merge only when it lives on the
            # MAJOR (outermost non-size-1) dim of the group and the
            # receiving major dim divides by the axis product
            major_s = [d for d in sdims if src[d] > 1]
            major_d = [d for d in ddims if dst[d] > 1]
            if len(sharded) == 1 and major_s and major_d and \
                    sharded[0][0] == major_s[0]:
                axes = sharded[0][1]
                n = _axes_product(axes, pl.mesh)
                if dst[major_d[0]] % max(1, n) == 0:
                    out_spec[major_d[0]] = axes
                    continue
            gathered.extend(a for _, e in sharded for a in e)
    for a in sorted(set(gathered)):
        if pl.mesh.get(a, 1) > 1:
            pl.emit("all_gather", (a,),
                    _aval_bytes(out.aval) / _axes_product([a], pl.mesh),
                    False, "reshape", mul)
    pl.set_spec(out, tuple(out_spec))


def _rule_reduce(pl: _Planner, eqn, mul: float):
    axes = tuple(eqn.params.get("axes", ()))
    in_v, out = eqn.invars[0], eqn.outvars[0]
    spec = pl.spec_of(in_v)
    out_spec = tuple(e for d, e in enumerate(spec) if d not in axes)
    reduce_axes = sorted({a for d in axes if d < len(spec)
                          for a in spec[d]})
    pl.set_spec(out, out_spec)
    if reduce_axes:
        payload = (_aval_bytes(out.aval)
                   / _shard_count(pl.spec_of(out), pl.mesh))
        pl.emit("all_reduce", tuple(reduce_axes), payload, True,
                eqn.primitive.name, mul)


def _rule_gather(pl: _Planner, eqn, mul: float):
    dn = eqn.params["dimension_numbers"]
    operand, indices = eqn.invars[0], eqn.invars[1]
    out = eqn.outvars[0]
    ospec = pl.spec_of(operand)
    ispec = pl.spec_of(indices)
    slice_sizes = tuple(eqn.params.get("slice_sizes", ()))
    op_shape = tuple(getattr(operand.aval, "shape", ()) or ())
    out_rank = len(out.aval.shape)
    offset = tuple(dn.offset_dims)
    collapsed = set(dn.collapsed_slice_dims)
    out_spec: List[Tuple[str, ...]] = [()] * out_rank
    # offset output dims ← non-collapsed operand dims, in order; the
    # spec survives only full (unsliced) dims
    slice_dims = [d for d in range(len(op_shape)) if d not in collapsed]
    for pos, d in zip(sorted(offset), slice_dims):
        full = (d < len(slice_sizes)
                and int(slice_sizes[d]) == int(op_shape[d]))
        if full:
            out_spec[pos] = ospec[d]
    # batch output dims ← indices dims (minus the index vector dim)
    batch_pos = [p for p in range(out_rank) if p not in offset]
    for p, d in zip(batch_pos, range(len(ispec))):
        out_spec[p] = ispec[d]
    used: set = set()
    final = pl._dedupe(tuple(out_spec), used, _aval_bytes(out.aval),
                       "gather", mul)
    pl.set_spec(out, final)
    # the vocab-parallel pattern: looking up along a SHARDED operand dim
    # lowers to a masked local lookup + one planned all-reduce
    lookup_axes = sorted({a for d in range(len(op_shape))
                          if d in collapsed or (
                              d < len(slice_sizes)
                              and int(slice_sizes[d]) < int(op_shape[d]))
                          for a in ospec[d]})
    if lookup_axes:
        payload = (_aval_bytes(out.aval)
                   / _shard_count(pl.spec_of(out), pl.mesh))
        moe = pl.moe
        # MoE combine: tokens read their slots back out of the
        # expert-sharded [E, C, M] buffer — each chip redistributes its
        # local expert slice over the expert axis (all_to_all of the
        # local slice), rather than all-reducing the gathered output
        if (moe is not None and len(op_shape) >= 2
                and int(op_shape[0]) == int(moe.experts)
                and int(op_shape[1]) == int(moe.capacity)
                and moe.expert_axis in lookup_axes):
            local = (_aval_bytes(operand.aval)
                     / _shard_count(ospec, pl.mesh))
            pl.emit("all_to_all", (moe.expert_axis,), local, True,
                    "gather(moe_combine)", mul)
            rest = tuple(a for a in lookup_axes if a != moe.expert_axis)
            if rest:
                pl.emit("all_reduce", rest, payload, True, "gather", mul)
        else:
            pl.emit("all_reduce", tuple(lookup_axes), payload, True,
                    "gather", mul)


def _rule_scatter(pl: _Planner, eqn, mul: float):
    operand, updates = eqn.invars[0], eqn.invars[-1]
    out = eqn.outvars[0]
    ospec = pl.spec_of(operand)
    pl.set_spec(out, ospec)
    # scatter-add into a differently-sharded target (embedding grad):
    # each chip owns partial updates — a planned grad-sync all-reduce
    if eqn.primitive.name in ("scatter-add", "scatter_add"):
        op_axes = {a for e in ospec for a in e}
        upd_axes = {a for e in pl.spec_of(updates) for a in e}
        sync = sorted(upd_axes - op_axes)
        if sync:
            payload = (_aval_bytes(out.aval)
                       / _shard_count(ospec, pl.mesh))
            pl.emit("all_reduce", tuple(sync), payload, True,
                    eqn.primitive.name, mul)


def _rule_concatenate(pl: _Planner, eqn, mul: float):
    dim = int(eqn.params["dimension"])
    out = eqn.outvars[0]
    rank = len(out.aval.shape)
    merged: List[Tuple[str, ...]] = [()] * rank
    for v in eqn.invars:
        if isinstance(v, jax.core.Literal):
            continue
        spec = pl.spec_of(v)
        for d in range(min(rank, len(spec))):
            if d != dim and spec[d] and not merged[d]:
                merged[d] = spec[d]
    used: set = set()
    pl.set_spec(out, pl._dedupe(tuple(merged), used,
                                _aval_bytes(out.aval), "concatenate", mul))


def _rule_squeeze(pl: _Planner, eqn, mul: float):
    dims = set(eqn.params.get("dimensions", ()))
    spec = pl.spec_of(eqn.invars[0])
    pl.set_spec(eqn.outvars[0],
                tuple(e for d, e in enumerate(spec) if d not in dims))


def _rule_expand_dims(pl: _Planner, eqn, mul: float):
    dims = set(eqn.params.get("dimensions", ()))
    spec = list(pl.spec_of(eqn.invars[0]))
    out_rank = len(eqn.outvars[0].aval.shape)
    out_spec: List[Tuple[str, ...]] = []
    it = iter(spec)
    for d in range(out_rank):
        out_spec.append(() if d in dims else next(it, ()))
    pl.set_spec(eqn.outvars[0], tuple(out_spec))


def _rule_shape_preserving(pl: _Planner, eqn, mul: float):
    """Ops where output dims correspond 1:1 to input dims but a dim's
    EXTENT may shrink (slice, pad, dynamic_slice...): keep the spec on
    untouched dims, drop it where the extent changed."""
    in_v, out = eqn.invars[0], eqn.outvars[0]
    spec = pl.spec_of(in_v)
    in_shape = tuple(getattr(in_v.aval, "shape", ()) or ())
    out_shape = tuple(out.aval.shape)
    if len(in_shape) != len(out_shape):
        pl.set_spec(out, _rep(len(out_shape)))
        return
    pl.set_spec(out, tuple(
        spec[d] if in_shape[d] == out_shape[d] else ()
        for d in range(len(out_shape))))


def _rule_dynamic_update_slice(pl: _Planner, eqn, mul: float):
    pl.set_spec(eqn.outvars[0], pl.spec_of(eqn.invars[0]))


def _rule_replicated(pl: _Planner, eqn, mul: float):
    for out in eqn.outvars:
        pl.set_spec(out, _rep(_rank(out)))


def _rule_top_k(pl: _Planner, eqn, mul: float):
    """top_k reduces the trailing dim to k: leading dims keep their
    sharding, the shrunken last dim replicates (MoE routing keeps its
    token sharding through the expert choice)."""
    spec = pl.spec_of(eqn.invars[0])
    out_spec = (spec[:-1] + ((),)) if spec else ()
    for out in eqn.outvars:
        pl.set_spec(out, out_spec)


def _rule_ppermute(pl: _Planner, eqn, mul: float):
    """One ring hop: every chip forwards its LOCAL buffer to one
    neighbor over a single ICI edge, so wire bytes = the payload itself
    (factor 1.0), not the ring ``(n-1)/n`` formula.  The ×ring-length
    multiplier arrives through ``mul``: ring attention's fori_loop
    lowers to a scan whose trip count is the ring length."""
    axes = eqn.params.get("axis_name", ())
    if isinstance(axes, str):
        axes = (axes,)
    axes = tuple(str(a) for a in (axes or ()))
    for v, out in zip(eqn.invars, eqn.outvars):
        payload = (_aval_bytes(getattr(v, "aval", None) or out.aval)
                   / _shard_count(pl.spec_of(v), pl.mesh))
        pl.emit("ppermute", axes, payload, True, "ppermute", mul,
                factor=1.0)
        pl.set_spec(out, pl.spec_of(v))


def _names_to_spec(names, rank: int) -> ShardSpec:
    """shard_map in_names/out_names entry ({dim: (axes, ...)}) → spec."""
    spec: List[Tuple[str, ...]] = [()] * rank
    if isinstance(names, dict):
        for dim, axes in names.items():
            d = int(dim)
            if 0 <= d < rank:
                if isinstance(axes, str):
                    axes = (axes,)
                spec[d] = tuple(str(a) for a in axes)
        return tuple(spec)
    return _normalize_spec(names, rank)


def _rule_shard_map(pl: _Planner, eqn, mul: float):
    """Recurse into the per-shard body.  Inner avals are already LOCAL
    (divided by the axes in in_names), so inner invars start replicated
    — every byte and collective payload inside is per-chip as-is — and
    the outer outputs take their global spec straight from out_names."""
    inner = eqn.params["jaxpr"]
    inner = getattr(inner, "jaxpr", inner)
    for iv in inner.invars:
        pl.set_spec(iv, _rep(_rank(iv)))
    pl.run(inner, mul)
    out_names = tuple(eqn.params.get("out_names", ()) or ())
    for i, ov in enumerate(eqn.outvars):
        rank = _rank(ov)
        if i < len(out_names):
            pl.set_spec(ov, _names_to_spec(out_names[i], rank))
        else:
            pl.set_spec(ov, _rep(rank))


def _make_collective_rule(kind: str):
    def rule(pl: _Planner, eqn, mul: float):
        axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if isinstance(axes, str):
            axes = (axes,)
        axes = tuple(str(a) for a in (axes or ()))
        for v, out in zip(eqn.invars, eqn.outvars):
            payload = (_aval_bytes(getattr(v, "aval", None) or out.aval)
                       / _shard_count(pl.spec_of(v), pl.mesh))
            pl.emit(kind, axes, payload, True, eqn.primitive.name, mul)
            pl.set_spec(out, _rep(_rank(out)))
    return rule


_RULES = {
    "dot_general": _rule_dot_general,
    "transpose": _rule_transpose,
    "broadcast_in_dim": _rule_broadcast_in_dim,
    "reshape": _rule_reshape,
    "reduce_sum": _rule_reduce,
    "reduce_max": _rule_reduce,
    "reduce_min": _rule_reduce,
    "reduce_prod": _rule_reduce,
    "reduce_and": _rule_reduce,
    "reduce_or": _rule_reduce,
    "argmax": _rule_reduce,
    "argmin": _rule_reduce,
    "gather": _rule_gather,
    "scatter": _rule_scatter,
    "scatter-add": _rule_scatter,
    "scatter_add": _rule_scatter,
    "concatenate": _rule_concatenate,
    "squeeze": _rule_squeeze,
    "expand_dims": _rule_expand_dims,
    "slice": _rule_shape_preserving,
    "dynamic_slice": _rule_shape_preserving,
    "pad": _rule_shape_preserving,
    "rev": _rule_shape_preserving,
    "dynamic_update_slice": _rule_dynamic_update_slice,
    "iota": _rule_replicated,
    "psum": _make_collective_rule("all_reduce"),
    "all_gather": _make_collective_rule("all_gather"),
    "psum_scatter": _make_collective_rule("reduce_scatter"),
    "all_to_all": _make_collective_rule("all_to_all"),
    "ppermute": _rule_ppermute,
    "shard_map": _rule_shard_map,
    "top_k": _rule_top_k,
}


# ---------------------------------------------------------------------------
# plan_jaxpr — the core entry every wrapper funnels into
# ---------------------------------------------------------------------------

def plan_jaxpr(closed, invar_specs: Sequence[Any], *,
               mesh: Dict[str, int],
               name: str = "<jaxpr>",
               chip: str = "cpu",
               hbm_budget_bytes: Optional[int] = None,
               constvar_specs: Optional[Sequence[Any]] = None,
               extra_var_specs: Sequence[Tuple[Any, Any]] = (),
               param_info: Sequence[Tuple[str, int, Any]] = (),
               data_inputs: Sequence[Tuple[str, int]] = (),
               data_axis: str = "data",
               s205_bytes: int = 1 << 20,
               s206_bytes: int = 8 << 20,
               moe: Optional[MoEStatics] = None,
               topology: Optional[Topology] = None,
               step_kind: Optional[str] = None) -> PlanReport:
    """Propagate ``invar_specs`` (one PartitionSpec-like or None per
    jaxpr invar; ``constvar_specs`` likewise for constvars) through
    ``closed`` on the abstract ``mesh`` and build the
    :class:`PlanReport`.

    ``param_info`` is ``[(name, nbytes, spec)]`` for S206;
    ``data_inputs`` is ``[(label, invar_index)]`` naming which invars
    carry a batch dimension S208 should check.  A ``topology``
    hierarchically decomposes host-spanning collectives into per-link
    ICI/DCN phases; ``step_kind`` names the registered step kind for
    the S213 latency-criticality check.
    """
    profile = CHIPS[chip] if isinstance(chip, str) else chip
    mesh = {str(k): int(v) for k, v in dict(mesh).items()}
    if topology is not None:
        topology.validate(mesh)
    n_chips = 1
    for v in mesh.values():
        n_chips *= v
    jaxpr = closed.jaxpr
    pl = _Planner(mesh, profile, moe=moe)
    for v, spec in zip(jaxpr.invars, list(invar_specs) or []):
        pl.set_spec(v, _normalize_spec(spec, _rank(v)))
    for v, spec in zip(jaxpr.constvars, list(constvar_specs or [])):
        pl.set_spec(v, _normalize_spec(spec, _rank(v)))
    for v, spec in extra_var_specs:
        pl.set_spec(v, _normalize_spec(spec, _rank(v)))
    pl.run(jaxpr)

    # hierarchical decomposition: each flat collective whose axes span
    # hosts becomes per-link phases, re-priced against the matching
    # link profile; the flat list survives for the layout recommender
    flat_collectives = pl.collectives
    if topology is None:
        collectives = flat_collectives
    else:
        collectives = []
        for c in flat_collectives:
            pay = float(c.payload_bytes)
            f0 = (c.bytes_moved / pay
                  if c.kind == "ppermute" and pay else None)
            for ph in topology.phases(c.kind, c.axes, pay, mesh,
                                      factor=f0):
                moved = int(ph.payload_bytes * ph.factor)
                if moved <= 0:
                    continue
                collectives.append(Collective(
                    kind=ph.kind, axes=ph.axes,
                    payload_bytes=int(ph.payload_bytes),
                    bytes_moved=moved,
                    time_s=estimate_collective_time(moved, profile,
                                                    level=ph.level),
                    planned=c.planned, primitive=c.primitive,
                    count=c.count, level=ph.level))

    # whole-program cost (all chips) for the S207 comparison
    acc: Dict[str, List[float]] = {}
    _collect_costs(jaxpr, 1.0, acc)
    flops = sum(v[0] for v in acc.values())
    byts = sum(v[1] for v in acc.values())

    def sharded_bytes(v) -> int:
        b = _var_bytes(v)
        if isinstance(v, jax.core.Literal) or b == 0:
            return b
        n = _shard_count(pl.spec_of(v), pl.mesh)
        return -(-b // n)  # ceil: padding never under-counts

    peak, peak_by_dtype = _peak_live_by_dtype(jaxpr, sharded_bytes)

    where = f"shardplan:{name}"
    diags: List[Diagnostic] = []

    # S205 — resharding hotspots: unplanned gathers grouped per
    # (primitive, axes) edge so one conflicted layer reads as one finding
    grouped: Dict[Tuple[str, Tuple[str, ...], str], float] = {}
    for c in pl.collectives:
        if not c.planned:
            key = (c.primitive, c.axes, c.kind)
            grouped[key] = grouped.get(key, 0.0) + c.total_bytes
    for (prim, axes, kind), total in sorted(grouped.items()):
        if total >= s205_bytes:
            diags.append(Diagnostic(
                "S205", ERROR,
                f"resharding hotspot: spec conflict at '{prim}' forces an "
                f"unplanned {kind} over mesh axes {list(axes)} moving "
                f"{total / 1024:.1f} KiB/chip — the layout fights itself "
                "on this edge; re-shard the producer or consumer so both "
                "agree", where))

    # S206 — fully-replicated large parameter: every chip burns its
    # full size (undonated-style HBM waste times the whole mesh)
    for pname, nbytes, spec in param_info:
        nspec = _normalize_spec(spec, len(spec or ()))
        if any(e for e in nspec) or nbytes < s206_bytes:
            continue
        diags.append(Diagnostic(
            "S206", WARNING,
            f"param {pname!r} ({nbytes / 2**20:.1f} MiB) is fully "
            f"replicated across all {n_chips} chips — "
            f"{nbytes * n_chips / 2**20:.1f} MiB of mesh HBM for one "
            "tensor; shard it on 'fsdp' unless it is genuinely tiny",
            where))

    # S207 — collective-bound step, level-aware: the bound is the
    # slowest link the step actually touches, not aggregate bandwidth
    comm_t = sum(c.total_time_s for c in collectives)
    compute_t = estimate_compute_time(flops / max(1, n_chips),
                                      byts / max(1, n_chips), profile)
    if comm_t > compute_t:
        ici_t = sum(c.total_time_s for c in collectives
                    if c.level != "dcn")
        dcn_t = comm_t - ici_t
        if topology is not None and dcn_t > 0:
            slow = "DCN" if dcn_t >= ici_t else "ICI"
            split = (f" (ICI {ici_t * 1e6:.1f} µs + DCN "
                     f"{dcn_t * 1e6:.1f} µs; bound by the {slow} link)")
            hint = ("move the heaviest axis onto ICI, shard less "
                    "aggressively, or grow the per-chip work")
        else:
            split = ""
            hint = ("shard less aggressively or grow the per-chip "
                    "work")
            slow = "ICI"
        diags.append(Diagnostic(
            "S207", ERROR,
            f"collective-bound: estimated comm {comm_t * 1e6:.1f} µs "
            f"exceeds per-chip compute {compute_t * 1e6:.1f} µs on "
            f"{profile.name}{split} — the mesh spends the step waiting "
            f"on {slow}; {hint}", where))

    # S208 — batch dim not on the data axis
    d_size = mesh.get(data_axis, 1)
    if d_size > 1:
        for label, idx in data_inputs:
            if idx >= len(jaxpr.invars):
                continue
            v = jaxpr.invars[idx]
            shape = tuple(getattr(v.aval, "shape", ()) or ())
            if not shape or shape[0] <= 1 or shape[0] % d_size != 0:
                continue  # batch=1 (chunked prefill) legitimately can't
            spec = pl.spec_of(v)
            if data_axis not in (spec[0] if spec else ()):
                diags.append(Diagnostic(
                    "S208", WARNING,
                    f"batch dim of input {label!r} {shape} is not sharded "
                    f"on the {data_axis!r} axis (size {d_size}) — the "
                    "whole batch is replicated; data parallelism buys "
                    "nothing for this input", where))

    # S210 — unpriced collective primitive: the plan silently omits its
    # traffic, which defeats the whole point of planning first
    for prim, axes in sorted(set(pl.unknown_collectives)):
        diags.append(Diagnostic(
            "S210", ERROR,
            f"unpriced collective primitive '{prim}' over mesh axes "
            f"{list(axes) or '<unknown>'}: the planner has no "
            "propagation/pricing rule for it, so its wire traffic is "
            "MISSING from this plan — add a rule to shardplan._RULES "
            "before trusting any number in this report", where))

    # S211 — static expert capacity overflow: top-k routing mass vs the
    # declared capacity-padded buffer; overflowing slots drop tokens
    if moe is not None:
        demand = int(moe.tokens) * int(moe.top_k)
        supply = int(moe.experts) * int(moe.capacity)
        if demand > supply:
            diags.append(Diagnostic(
                "S211", ERROR,
                f"static expert capacity overflow: {moe.tokens} tokens × "
                f"top-{moe.top_k} = {demand} routed slots but E×C = "
                f"{moe.experts}×{moe.capacity} = {supply} at capacity "
                f"factor {moe.capacity_factor:g} — "
                f"{demand - supply} routing choices are statically "
                "guaranteed to drop; raise the capacity factor or the "
                "expert count", where))

    # S212 — ring hop that cannot hide under compute: the per-hop
    # permute must overlap one hop's worth of local attention compute
    # (ICI hops only — a DCN-priced hop is S215's finding)
    for c in collectives:
        if c.kind != "ppermute" or c.level == "dcn":
            continue
        hops = max(1.0, float(c.count))
        window = compute_t / hops
        if c.time_s > window:
            diags.append(Diagnostic(
                "S212", WARNING,
                f"ring/sp hop moves {c.bytes_moved / 1024:.1f} KiB over "
                f"{list(c.axes)} taking {c.time_s * 1e6:.1f} µs on "
                f"{profile.name} ICI, but only {window * 1e6:.1f} µs of "
                "per-hop compute exists to hide it — the ring is "
                "ICI-bound; grow the per-chip sequence chunk or use a "
                "faster interconnect", where))

    # S213 — DCN-crossing collective inside a latency-critical step:
    # decode/prefill sit on the request critical path, and one 10 µs+
    # DCN round per layer is the difference between serving and not.
    # Edges under the floor (scalar-sized control reduces the
    # conservative gather rule prices) stay priced but unflagged.
    if topology is not None and step_kind in LATENCY_CRITICAL_STEP_KINDS:
        edge_bytes: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        for c in collectives:
            if c.level == "dcn":
                key = (c.kind, c.axes)
                edge_bytes[key] = edge_bytes.get(key, 0.0) + c.total_bytes
        hot = {k: b for k, b in edge_bytes.items()
               if b >= _S213_FLOOR_BYTES}
        if hot:
            total = sum(hot.values())
            n = sum(1 for c in collectives if c.level == "dcn"
                    and (c.kind, c.axes) in hot)
            edges = sorted(f"{kind} over {'×'.join(axes)}"
                           for kind, axes in hot)
            diags.append(Diagnostic(
                "S213", ERROR,
                f"DCN-crossing collective in latency-critical step "
                f"kind {step_kind!r}: {n} phase(s) "
                f"({'; '.join(edges)}) move {total / 1024:.1f} KiB/chip "
                "over the data-center network on the request critical "
                "path — keep every serving axis (tp/sp) inside one "
                "host's ICI domain and cross hosts only on the batch "
                "axis, which decode never reduces over", where))

    # S214 — a hotter axis rides DCN while a colder same-size axis
    # rides ICI: swapping the assignment is free at plan time
    if topology is not None:
        axis_splits = topology.splits(mesh)
        traffic: Dict[str, float] = {}
        for c in flat_collectives:
            for a in c.axes:
                traffic[a] = traffic.get(a, 0.0) + c.total_bytes
        dcn_axes = [a for a in mesh
                    if axis_splits.get(a, (1, 1))[1] > 1]
        ici_axes = [a for a in mesh if mesh[a] > 1
                    and axis_splits.get(a, (1, 1))[1] == 1]
        best = None
        for d in dcn_axes:
            for i in ici_axes:
                if mesh[d] != mesh[i]:
                    continue  # unequal sizes: swap changes the layout
                gain = traffic.get(d, 0.0) - traffic.get(i, 0.0)
                if gain > 0 and (best is None or gain > best[0]):
                    best = (gain, d, i)
        if best is not None:
            _, d, i = best
            diags.append(Diagnostic(
                "S214", WARNING,
                f"high-traffic axis {d!r} "
                f"({traffic.get(d, 0.0) / 1024:.1f} KiB/chip) is mapped "
                f"to DCN while axis {i!r} "
                f"({traffic.get(i, 0.0) / 1024:.1f} KiB/chip) rides "
                f"ICI — both are size {mesh[d]}; swap the assignment "
                f"(axis_levels={{{i!r}: 'dcn', {d!r}: 'ici'}}) to move "
                "the heavy traffic onto the fast link", where))

    # S215 — DCN phase that cannot hide behind the step's compute
    # window (the cross-host mirror of S212's ICI check); one finding
    # per (kind, axes) edge, reporting its slowest phase
    if topology is not None:
        worst: Dict[Tuple[str, Tuple[str, ...]], Collective] = {}
        for c in collectives:
            if c.level != "dcn":
                continue
            window = compute_t / max(1.0, float(c.count))
            if c.time_s <= window:
                continue
            key = (c.kind, c.axes)
            if key not in worst or c.time_s > worst[key].time_s:
                worst[key] = c
        for (kind, axes), c in sorted(worst.items()):
            window = compute_t / max(1.0, float(c.count))
            diags.append(Diagnostic(
                "S215", WARNING,
                f"DCN phase {kind} over {list(axes)} moves "
                f"{c.bytes_moved / 1024:.1f} KiB/chip taking "
                f"{c.time_s * 1e6:.1f} µs on {profile.name} DCN, but "
                f"only {window * 1e6:.1f} µs of per-occurrence compute "
                "exists to hide it — the cross-host traffic sits "
                "exposed on the step's critical path; overlap it "
                "against compute or move the axis onto ICI", where))

    if hbm_budget_bytes is not None and peak > hbm_budget_bytes:
        diags.append(Diagnostic(
            "H110", ERROR,
            f"per-chip peak live HBM {peak / 2**30:.3f} GiB exceeds the "
            f"{hbm_budget_bytes / 2**30:.3f} GiB per-chip budget on this "
            f"{_mesh_str(mesh)} mesh — shard further, shrink the batch, "
            "or pick a bigger chip", where))

    from .hazards import sort_diagnostics

    param_specs = {pname: _spec_str(_normalize_spec(spec, len(spec or ())))
                   for pname, _, spec in param_info}
    return PlanReport(
        name=name, chip=profile, mesh=mesh, n_chips=n_chips,
        per_chip_peak_hbm_bytes=peak, collectives=collectives,
        flops=flops, bytes=byts, diagnostics=sort_diagnostics(diags),
        param_specs=param_specs, hbm_budget_bytes=hbm_budget_bytes,
        per_chip_peak_hbm_by_dtype=peak_by_dtype, topology=topology,
        flat_collectives=flat_collectives, step_kind=step_kind)


def _mesh_str(mesh: Dict[str, int]) -> str:
    return "(" + ",".join(f"{k}={v}" for k, v in mesh.items()) + ")"


# ---------------------------------------------------------------------------
# wrappers: train step, serving step, the default audit
# ---------------------------------------------------------------------------

def _param_names(sfn) -> Dict[int, str]:
    """id(param) → qualified name, walked over the layers the static
    function discovered (the model is always among them)."""
    names: Dict[int, str] = {}
    for layer in (sfn._layers or ()):
        for n, p in layer.named_parameters():
            names.setdefault(id(p), n)
    return names


def plan_train_step(step_fn, inputs, labels, *,
                    request: Optional[PlanRequest] = None,
                    name: str = "hapi::train_step") -> PlanReport:
    """Plan a ``jit.to_static`` train step (or its observability
    wrapper) on sample ``inputs``/``labels``.  The trace's invar layout
    is ``state ++ dyn ++ lrs ++ rng``; params take the layout's role
    spec, optimizer slots inherit their param's spec, inputs take the
    batch spec, everything else replicates."""
    req = request or PlanRequest()
    layout = req.resolved_layout()
    sfn = getattr(step_fn, "_fn", step_fn)
    closed, _donated = sfn.trace_jaxpr(inputs, labels)
    state = sfn._state
    names = _param_names(sfn)
    by_id: Dict[int, Any] = {}
    param_info: List[Tuple[str, int, Any]] = []
    for i, p in enumerate(state.params):
        pname = names.get(id(p), f"param{i}")
        spec = layout.param_spec(pname)
        by_id[id(p)] = spec
        param_info.append((pname, _aval_bytes(p._value), spec))

    n_in = len(closed.jaxpr.invars)
    n_p, n_b = len(state.params), len(state.buffers)
    slots = state.opt_slots()
    specs: List[Any] = [None] * n_in
    for i, p in enumerate(state.params):
        if i < n_in:
            specs[i] = by_id[id(p)]
    for j, (_store, key) in enumerate(slots):
        idx = n_p + n_b + j
        if idx < n_in and key in by_id:
            specs[idx] = by_id[key]      # slot keyed by id(param)
    dyn_lo, dyn_hi = n_p + n_b + len(slots), n_in - 2
    data_inputs: List[Tuple[str, int]] = []
    batch = layout.batch_spec()
    for idx in range(dyn_lo, dyn_hi):
        specs[idx] = batch
        data_inputs.append((f"dyn{idx - dyn_lo}", idx))
    return plan_jaxpr(
        closed, specs, mesh=req.mesh, name=name, chip=req.chip,
        hbm_budget_bytes=req.hbm_budget_bytes, param_info=param_info,
        data_inputs=data_inputs, data_axis=layout.data_axis,
        s205_bytes=req.s205_bytes, s206_bytes=req.s206_bytes,
        moe=req.moe, topology=req.topology, step_kind="train")


def plan_step(step, abstract_args: Sequence[Any], *, model,
              arg_specs: Sequence[Any],
              request: Optional[PlanRequest] = None,
              name: str = "<step>",
              data_input_leaves: Sequence[Tuple[str, int]] = (),
              step_kind: Optional[str] = None) -> PlanReport:
    """Plan a serving-style step traced with ``jax.make_jaxpr``.  The
    model weights are captured as jit CONSTANTS, so they surface as
    jaxpr constvars — matched back to named parameters by identity.
    ``arg_specs`` mirrors ``abstract_args``' pytree structure;
    ``data_input_leaves`` names flat leaf indices S208 should check."""
    from .xray import _as_abstract

    req = request or PlanRequest()
    layout = req.resolved_layout()
    fn = step
    if hasattr(fn, "_fn") and hasattr(fn, "compiles"):
        fn = fn._fn
    args = [jax.tree_util.tree_map(_as_abstract, a,
                                   is_leaf=lambda x: hasattr(x, "_value"))
            for a in abstract_args]
    closed = jax.make_jaxpr(fn)(*args)
    flat_specs: List[Any] = []
    for spec, arg in zip(arg_specs, args):
        _flatten_specs_like(spec, arg, flat_specs)
    # jitted steps trace to one pjit eqn: the captured weights are
    # consts of NESTED closed jaxprs, not the top level — walk them all
    by_value: Dict[int, str] = {id(p._value): n
                                for n, p in model.named_parameters()}
    extra: List[Tuple[Any, Any]] = []
    param_info: List[Tuple[str, int, Any]] = []
    seen: set = set()
    for var, val in _iter_const_bindings(closed):
        pname = by_value.get(id(val))
        if pname is None:
            continue
        spec = layout.param_spec(pname)
        extra.append((var, spec))
        if pname not in seen:
            seen.add(pname)
            param_info.append((pname, _var_bytes(var), spec))
    return plan_jaxpr(
        closed, flat_specs, mesh=req.mesh, name=name, chip=req.chip,
        hbm_budget_bytes=req.hbm_budget_bytes,
        extra_var_specs=extra, param_info=param_info,
        data_inputs=data_input_leaves, data_axis=layout.data_axis,
        s205_bytes=req.s205_bytes, s206_bytes=req.s206_bytes,
        moe=req.moe, topology=req.topology, step_kind=step_kind)


def _iter_const_bindings(closed):
    """Yield ``(constvar, const_value)`` pairs for a ClosedJaxpr and
    every ClosedJaxpr nested in its equations (pjit / scan / while /
    cond / custom_* all carry their own consts)."""
    yield from zip(closed.jaxpr.constvars, closed.consts)
    for eqn in closed.jaxpr.eqns:
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                    "body_jaxpr"):
            inner = eqn.params.get(key)
            if inner is not None and hasattr(inner, "consts"):
                yield from _iter_const_bindings(inner)
        for b in eqn.params.get("branches", ()):
            if hasattr(b, "consts"):
                yield from _iter_const_bindings(b)


def _flatten_specs_like(spec, arg, out: List[Any]):
    """Walk ``spec`` alongside ``arg``'s container structure, emitting
    one spec per array leaf in jax flattening order.  A PartitionSpec
    (or None) against a container broadcasts over every leaf under it."""
    from jax.sharding import PartitionSpec

    if isinstance(arg, dict):
        for k in sorted(arg):
            sub = spec.get(k) if isinstance(spec, dict) else spec
            _flatten_specs_like(sub, arg[k], out)
        return
    if isinstance(arg, (list, tuple)):
        broadcast = (spec is None or isinstance(spec, PartitionSpec)
                     or not isinstance(spec, (list, tuple)))
        for i, a in enumerate(arg):
            _flatten_specs_like(spec if broadcast else spec[i], a, out)
        return
    out.append(spec)


def _serving_arg_specs(model, layout, decode_args, prefill_args):
    """Specs mirroring ``xray._serving_abstract_args``' structure: KV
    pools shard kv-heads on ``tp`` (SNIPPETS [3] style), per-sequence
    buffers shard batch on ``data``; prefill runs batch=1, replicated.
    Quantized pool entries carry two extra per-row scale sidecars
    ([num_blocks, block_size], no kv-head axis) that REPLICATE — the
    spec tuples mirror the entry arity so spec flattening stays
    one-to-one with the args."""
    from jax.sharding import PartitionSpec

    tp = layout.tp_axis
    pool_spec = []
    for entry in decode_args[1]:
        specs = (PartitionSpec(None, None, tp, None),
                 PartitionSpec(None, None, tp, None))
        if len(entry) == 4:
            specs += (PartitionSpec(None, None),
                      PartitionSpec(None, None))
        pool_spec.append(specs)
    batch = layout.batch_spec()
    decode = (batch, pool_spec, batch, batch)
    prefill = (PartitionSpec(), pool_spec, PartitionSpec(),
               PartitionSpec(), PartitionSpec())
    return decode, prefill


#: audit_shardplan's default step set and the canonical mesh each step
#: falls back to when the caller's mesh lacks its required axis
DEFAULT_AUDIT_STEPS = ("train", "decode", "prefill", "sampled_decode",
                       "spec_verify", "moe", "ring")
_MOE_AUDIT_MESH = {"data": 2, "fsdp": 2, "expert": 2}
_RING_AUDIT_MESH = {"data": 2, "sp": 2, "tp": 2}


def audit_shardplan(*, chip: str = "cpu",
                    hbm_budget_bytes: Optional[int] = None,
                    mesh: Optional[Dict[str, int]] = None,
                    layout: Any = None,
                    s205_bytes: int = 1 << 10,
                    s206_bytes: int = 8 << 20,
                    steps: Sequence[str] = DEFAULT_AUDIT_STEPS,
                    topology: Optional[Topology] = None
                    ) -> List[PlanReport]:
    """Plan the default step kinds (train, paged decode, chunked
    prefill, MoE block, ring/sp block) for tiny Llamas against the
    canonical llama SpecLayout — entirely on CPU, no devices.  The
    ``lint_tpu.py --shardplan`` / CI entry point; callers gate on
    ``report.errors()``.

    Train/decode/prefill plan on the caller's mesh (default
    ``(data=2, fsdp=2, tp=2)``); the MoE step needs an ``expert`` axis
    and the ring step an ``sp`` axis, so each falls back to its
    canonical mesh (``_MOE_AUDIT_MESH`` / ``_RING_AUDIT_MESH``) when
    the caller's mesh lacks it.  ``steps`` filters which kinds run.

    The S205 threshold defaults to 1 KiB here (not the production
    1 MiB): the CI model is tiny, and a CLEAN layout emits zero
    unplanned collectives regardless of scale — any unplanned byte on
    this model means real conflict at any size."""
    import paddle_tpu as paddle
    from .. import nn
    from ..models import LlamaConfig, LlamaForCausalLM
    from ..optimizer import AdamW

    req = PlanRequest(mesh=mesh or {"data": 2, "fsdp": 2, "tp": 2},
                      layout=layout, chip=chip,
                      hbm_budget_bytes=hbm_budget_bytes,
                      s205_bytes=s205_bytes, s206_bytes=s206_bytes,
                      topology=topology)
    lay = req.resolved_layout()
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    net = LlamaForCausalLM(cfg)
    reports: List[PlanReport] = []

    if "train" in steps:
        model = paddle.Model(net)
        model.prepare(AdamW(1e-3, parameters=net.parameters()),
                      nn.CrossEntropyLoss())
        ids = np.zeros((2, 16), np.int64)
        reports.append(plan_train_step(
            model._train_step_fn, [paddle.to_tensor(ids[:, :-1])],
            [paddle.to_tensor(ids[:, 1:])], request=req))

    from ..models.generation import (make_chunked_prefill_step,
                                     make_moe_block_step,
                                     make_paged_decode_step,
                                     make_ring_sp_step)
    from .xray import _serving_abstract_args

    net.eval()
    serving_kinds = {"decode", "prefill", "fused_decode", "fused_prefill",
                     "sampled_decode", "spec_verify"}
    if serving_kinds & set(steps):
        decode_args, prefill_args = _serving_abstract_args(
            net, batch=4, num_blocks=32, block_size=8,
            max_blocks_per_seq=8, chunk_tokens=32)
        decode_specs, prefill_specs = _serving_arg_specs(
            net, lay, decode_args, prefill_args)
        if "decode" in steps:
            reports.append(plan_step(
                make_paged_decode_step(net), decode_args, model=net,
                arg_specs=decode_specs, request=req,
                name="serving::decode_step",
                data_input_leaves=(("tokens", 0),),
                step_kind="paged_decode"))
        if "prefill" in steps:
            reports.append(plan_step(
                make_chunked_prefill_step(net), prefill_args, model=net,
                arg_specs=prefill_specs, request=req,
                name="serving::prefill_step",
                data_input_leaves=(("chunk_ids", 0),),
                step_kind="chunked_prefill"))
        # fused serving steps (kernels/fusion forced on, XLA fallback
        # off-TPU): same shapes and latency-critical step kinds as the
        # unfused plans — the CI gate that the fused programs plan
        # without S210 unknown-collective blind spots
        # sampled decode + speculative verify (ISSUE 19): the decode/
        # chunked-prefill shapes plus per-slot sampling state.  All the
        # sampling-state arrays are slot-indexed, so they shard exactly
        # like the batch inputs; draft proposal distributions [S, K, V]
        # likewise shard on the slot dim only.
        if {"sampled_decode", "spec_verify"} & set(steps):
            from ..serving.sampling import make_sampled_decode_step
            from ..serving.speculative import make_spec_verify_step

            sds_ = jax.ShapeDtypeStruct
            s_batch, num_draft = 4, 4
            b_spec = lay.batch_spec()
            sampling_args = (sds_((s_batch,), np.float32),
                             sds_((s_batch,), np.int32),
                             sds_((s_batch,), np.float32),
                             sds_((s_batch, 2), np.uint32),
                             sds_((s_batch,), np.int32))
            sampling_specs = (b_spec,) * 5
            if "sampled_decode" in steps:
                reports.append(plan_step(
                    make_sampled_decode_step(net),
                    decode_args + sampling_args, model=net,
                    arg_specs=decode_specs + sampling_specs,
                    request=req, name="serving::sampled_decode_step",
                    data_input_leaves=(("tokens", 0),),
                    step_kind="sampled_decode"))
            if "spec_verify" in steps:
                pool_spec = decode_specs[1]
                verify_args = (
                    sds_((s_batch,), np.int32),
                    sds_((s_batch, num_draft), np.int32),
                    sds_((s_batch, num_draft, cfg.vocab_size),
                         np.float32),
                    decode_args[1], decode_args[2], decode_args[3]
                ) + sampling_args
                # slot-indexed verify args stay REPLICATED: the
                # acceptance math reshapes [S, K+1] into [S*(K+1)],
                # and a batch-sharded slot dim would turn that reshape
                # into data-axis collectives on the decode critical
                # path (S213).  The pool still shards on tp like the
                # plain decode step.
                from jax.sharding import PartitionSpec
                rep = PartitionSpec()
                verify_specs = (rep, rep, rep, pool_spec,
                                rep, rep) + (rep,) * 5
                reports.append(plan_step(
                    make_spec_verify_step(net, num_draft), verify_args,
                    model=net, arg_specs=verify_specs, request=req,
                    name="serving::spec_verify_step",
                    data_input_leaves=(("pending", 0),),
                    step_kind="spec_verify"))
        if "fused_decode" in steps:
            reports.append(plan_step(
                make_paged_decode_step(net, fused=True), decode_args,
                model=net, arg_specs=decode_specs, request=req,
                name="serving::decode_step[fused]",
                data_input_leaves=(("tokens", 0),),
                step_kind="paged_decode"))
        if "fused_prefill" in steps:
            reports.append(plan_step(
                make_chunked_prefill_step(net, fused=True), prefill_args,
                model=net, arg_specs=prefill_specs, request=req,
                name="serving::prefill_step[fused]",
                data_input_leaves=(("chunk_ids", 0),),
                step_kind="chunked_prefill"))

    sds = jax.ShapeDtypeStruct
    if "moe" in steps:
        from ..kernels.moe_dispatch import moe_capacity

        moe_mesh = (req.mesh if "expert" in (req.mesh or {})
                    else dict(_MOE_AUDIT_MESH))
        E, K, cf = 4, 2, 2.0
        B, T = 4, 16
        moe_req = dataclasses.replace(
            req, mesh=moe_mesh,
            moe=MoEStatics(experts=E, capacity=moe_capacity(B * T, E, K, cf),
                           top_k=K, tokens=B * T, capacity_factor=cf))
        moe_net = LlamaForCausalLM(LlamaConfig.tiny(
            moe_num_experts=E, moe_top_k=K, moe_capacity_factor=cf))
        moe_net.eval()
        reports.append(plan_step(
            make_moe_block_step(moe_net), (sds((B, T), np.int32),),
            model=moe_net, arg_specs=(lay.batch_spec(),),
            request=moe_req, name="moe::block_step",
            data_input_leaves=(("tokens", 0),),
            step_kind="moe_block"))

    if "ring" in steps:
        from ..distributed.mesh import abstract_mesh

        ring_mesh = (req.mesh if "sp" in (req.mesh or {})
                     else dict(_RING_AUDIT_MESH))
        ring_req = dataclasses.replace(req, mesh=ring_mesh, moe=None)
        ring_net = LlamaForCausalLM(LlamaConfig.tiny(
            context_parallel="ring"))
        ring_net.eval()
        reports.append(plan_step(
            make_ring_sp_step(ring_net, mesh=abstract_mesh(ring_mesh)),
            (sds((4, 32), np.int32),),
            model=ring_net, arg_specs=(lay.batch_spec(),),
            request=ring_req, name="ring::sp_step",
            data_input_leaves=(("tokens", 0),),
            step_kind="ring_sp"))

    for r in reports:
        export_plan_gauges(r)
    return reports


def export_plan_gauges(report: PlanReport):
    """Mirror a plan's headline numbers into the observability registry
    (no-op when telemetry is disabled)."""
    from .. import observability

    if not observability.enabled():
        return
    reg = observability.get_registry()
    reg.gauge("shardplan_comm_bytes",
              "total per-chip collective wire bytes of a planned step"
              ).set(report.comm_bytes, step=report.name)
    reg.gauge("shardplan_ici_comm_bytes",
              "per-chip wire bytes a planned step puts on intra-host ICI"
              ).set(report.ici_comm_bytes, step=report.name)
    reg.gauge("shardplan_dcn_comm_bytes",
              "per-chip wire bytes a planned step puts on cross-host DCN"
              ).set(report.dcn_comm_bytes, step=report.name)
    reg.gauge("shardplan_per_chip_peak_hbm_bytes",
              "shard-aware liveness peak HBM per chip of a planned step"
              ).set(report.per_chip_peak_hbm_bytes, step=report.name)
    g = reg.gauge("shardplan_per_chip_peak_hbm_bytes_by_dtype",
                  "per-chip bytes of one dtype at the liveness peak")
    for dt, b in sorted(report.per_chip_peak_hbm_by_dtype.items()):
        g.set(b, step=report.name, dtype=dt)


def recommend_layouts(report: PlanReport, *,
                      hosts: Optional[int] = None,
                      chips_per_host: Optional[Tuple[int, ...]] = None):
    """Rank every valid axis→level assignment for ``report``'s mesh by
    the comm time it would give this step — repricing the flat
    collective inventory the propagation already produced (no
    re-trace).  ``hosts`` defaults to the report's topology.  Returns
    :class:`~paddle_tpu.analysis.topology.RankedLayout` objects, best
    first; render with
    :func:`~paddle_tpu.analysis.topology.format_recommendations`."""
    if hosts is None:
        if report.topology is None:
            raise ValueError(
                "recommend_layouts needs hosts=: the report was "
                "planned without a Topology")
        hosts = report.topology.hosts
        if chips_per_host is None:
            chips_per_host = report.topology.chips_per_host
    flat = report.flat_collectives or report.collectives
    return rank_layouts(flat, report.mesh, report.chip, hosts,
                        chips_per_host)
