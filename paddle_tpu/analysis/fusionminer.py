"""Fusion-candidate miner: xray-driven static fusion analysis.

PR 13 fused the serving decode hot path BY HAND (paged gather + RoPE +
attention; RMSNorm→matmul prologues).  The fusion literature
(FusionStitching, arXiv:2009.10924; "Operator Fusion in XLA",
arXiv:2301.13062) argues the durable win is *systematic* discovery of
memory-bound fusion chains — so this module closes the ROADMAP's
"analysis-driven fusion expansion" loop: walk any traced step's jaxpr
with xray's cost model and let the analyzer rank the next kernel.

Algorithm (:func:`mine_jaxpr`):

1. **Classify** every equation at each jaxpr level (recursing through
   pjit/scan/cond/while/custom_* exactly like xray's ``_sub_jaxprs``;
   ``pallas_call`` is a priced leaf): matmuls/convs are *anchors*,
   elementwise/movement/reduction/transcendental equations are
   *fusible*, scatters/sorts/callbacks are *barriers*.  A call-like
   equation whose body is entirely fusible (jnp helpers like ``_take``,
   ``silu``, ``floor_divide``) is folded in as one fusible node instead
   of fragmenting the chain.
2. **Chain** fusible equations into maximal groups: a producer joins
   its consumers' group when every consumer of the connecting variable
   is fusible and lands in ONE group (single-consumer dataflow edges
   plus diamond closure — e.g. softmax's ``exp`` feeding both its
   ``reduce_sum`` and the final ``div``), iterated to a fixpoint.
3. **Absorb across anchors**: a chain output consumed only by matmuls
   can fuse as their prologue; a chain input produced by a matmul whose
   only consumer is the chain can fuse as its epilogue.  Chains
   connected through a *data* anchor (both operands locally produced —
   attention's score and context matmuls) merge into one region, the
   shape of a flash-attention kernel; *weight* anchors (an operand is a
   program input) bound regions the way a real GEMM bounds an XLA
   fusion group.
4. **Price** each region with xray's per-primitive byte model: an
   intermediate that stays in VMEM saves one HBM write + one read
   (``2 × bytes``); a chain output absorbed into ``n`` anchors saves
   ``(1 + n) × bytes``; scan-carried chains multiply by the trip
   count.  Time saved = bytes / the chip profile's HBM bandwidth (the
   roofline memory leg — these chains are memory-bound by
   construction).
5. **Rank and report** structurally-identical regions grouped by
   (code, source site, primitive signature) as F-series diagnostics:

   - **F001** fusible elementwise/movement chain (generic)
   - **F002** norm→matmul prologue candidate (reduce+rescale chain
     feeding only matmuls — the ``fused_norm_linear`` shape)
   - **F003** reduction→elementwise epilogue candidate (region
     containing a reduction downstream of an anchor — softmax /
     attention-region shape)
   - **F004** already-fused leaf (a priced ``pallas_call``; reported
     for coverage, excluded from ranking)

   Ranking: bytes-saved descending, ties by (file, line).  Diagnostics
   go through ``hazards.sort_diagnostics`` and honor the lint-tpu
   suppression comments (``# lint-tpu: disable=F001 -- reason`` on the
   flagged line, ``disable-file=`` anywhere in the file).

Surfaced by ``tools/lint_tpu.py --xray --fusion [--json]`` and the CI
fusion stage; validated in tests/test_fusionminer.py by rediscovering
both PR 13 hand-built fusions as the top-ranked candidates on the
unfused serving traces and as F004-covered on the fused ones.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .verifier import ERROR, INFO, WARNING, Diagnostic
from .hazards import _where_key, sort_diagnostics
from .xray import (CHIPS, ChipProfile, _as_abstract, _eqn_bytes,
                   _pallas_kernel_name, _sub_jaxprs, _var_bytes)

__all__ = [
    "FusionCandidate",
    "FusionReport",
    "audit_fusion",
    "mine",
    "mine_jaxpr",
]

_ANCHORS = {"dot_general", "conv_general_dilated"}
# fusible data movement; scatter/dynamic_update_slice rewrite a full
# buffer in place (the output escapes by construction) and sort/top_k
# reorder globally — none of those belong inside a memory-bound chain
_BARRIERS = {
    "scatter", "scatter_add", "scatter_mul", "scatter_min", "scatter_max",
    "dynamic_update_slice", "sort", "top_k", "copy", "device_put",
    "pure_callback", "io_callback", "outside_call", "debug_callback",
    "rng_bit_generator", "random_seed", "random_wrap", "random_bits",
    "infeed", "outfeed", "custom_call",
}
_REDUCES = ("reduce_", "cum", "arg")


# the repo's own op-dispatch plumbing: frames here emitted the eqn but
# the line a human would fuse (and suppress) lives one level up, in
# model/kernel code
_INTERNAL_FRAMES = (os.sep + os.path.join("paddle_tpu", "core") + os.sep,
                    os.sep + os.path.join("paddle_tpu", "ops") + os.sep,
                    os.sep + os.path.join("paddle_tpu", "nn") + os.sep)


def _source_where(eqn) -> str:
    """``file:line`` of the innermost NON-PLUMBING user frame that
    emitted ``eqn`` (the same location the lint-tpu suppression
    comments key on)."""
    try:
        from jax._src import source_info_util

        frames = list(source_info_util.user_frames(eqn.source_info))
    except Exception:  # pragma: no cover - jax internals moved
        frames = []
    frame = None
    for fr in frames:
        if not any(part in fr.file_name for part in _INTERNAL_FRAMES):
            frame = fr
            break
    if frame is None:
        frame = frames[0] if frames else None
    if frame is None:
        return "<unknown>:0"
    return f"{frame.file_name}:{frame.start_line}"


def _eqn_kind(eqn) -> str:
    name = eqn.primitive.name
    if name == "pallas_call":
        return "fused_leaf"
    if name in _ANCHORS:
        return "anchor"
    if name in _BARRIERS:
        return "barrier"
    if _sub_jaxprs(eqn):
        return "call"
    return "fusible"


def _transparent(jaxpr) -> bool:
    """A call body made ONLY of fusible equations (recursively): the
    call folds into the surrounding chain as one node instead of
    splitting it — jnp helpers (``_take``, ``_where``, ``silu``,
    ``floor_divide``) trace as tiny pjits."""
    for eqn in jaxpr.eqns:
        kind = _eqn_kind(eqn)
        if kind == "call":
            subs = _sub_jaxprs(eqn)
            if len(subs) != 1 or not _transparent(subs[0][0]):
                return False
        elif kind != "fusible":
            return False
    return True


def _inner_interior_bytes(jaxpr) -> float:
    """Bytes of a transparent call body's own intermediates (everything
    its equations define short of the body outputs)."""
    outs = set(v for v in jaxpr.outvars
               if not isinstance(v, jax.core.Literal))
    total = 0.0
    for eqn in jaxpr.eqns:
        for inner, _ in _sub_jaxprs(eqn):
            total += _inner_interior_bytes(inner)
        for v in eqn.outvars:
            if v not in outs and not isinstance(v, jax.core.DropVar):
                total += _var_bytes(v)
    return total


def _leaf_primitives(eqn) -> List[str]:
    subs = _sub_jaxprs(eqn)
    if not subs:
        return [eqn.primitive.name]
    names: List[str] = []
    for inner, _ in subs:
        for e in inner.eqns:
            names.extend(_leaf_primitives(e))
    return names


def _contains_reduce(eqn) -> bool:
    return any(p.startswith(_REDUCES) for p in _leaf_primitives(eqn))


class _UnionFind:
    def __init__(self):
        self.parent: Dict[int, int] = {}

    def find(self, x: int) -> int:
        root = x
        while self.parent.setdefault(root, root) != root:
            root = self.parent[root]
        while self.parent[x] != root:
            self.parent[x], x = root, self.parent[x]
        return root

    def union(self, a: int, b: int) -> bool:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return False
        self.parent[max(ra, rb)] = min(ra, rb)
        return True


@dataclasses.dataclass
class _Region:
    """One mined fusion region before cross-layer grouping."""

    code: str
    where: str
    path: str
    primitives: Tuple[str, ...]        # leaf primitive signature
    n_eqns: int
    bytes_saved: float
    prologue_anchors: Tuple[str, ...]  # anchor primitive names fed
    epilogue_anchors: Tuple[str, ...]  # anchor primitive names followed
    interior_anchors: int              # data matmuls inside the region


@dataclasses.dataclass
class FusionCandidate:
    """One ranked fusion opportunity (structurally identical regions
    grouped across layers/sites)."""

    code: str                  # F001 / F002 / F003
    where: str                 # file:line of the region's first eqn
    path: str                  # jaxpr call path ("pjit", "pjit/scan")
    primitives: Tuple[str, ...]
    n_eqns: int                # leaf eqns in ONE region
    count: int                 # structurally identical regions merged
    bytes_saved: float         # HBM round-trip bytes across all sites
    time_saved_s: float        # bytes_saved / chip HBM bandwidth
    prologue_anchors: Tuple[str, ...]
    epilogue_anchors: Tuple[str, ...]
    interior_anchors: int
    rank: Optional[int] = None
    suppressed: bool = False

    def describe(self) -> str:
        prims = ", ".join(self.primitives[:6])
        if len(self.primitives) > 6:
            prims += f", +{len(self.primitives) - 6} more"
        rank = f"#{self.rank}: " if self.rank else ""
        sites = f" x{self.count} site(s)" if self.count > 1 else ""
        edges = []
        if self.interior_anchors:
            edges.append(f"spans {self.interior_anchors} data matmul(s)")
        if self.epilogue_anchors:
            edges.append("follows " + "/".join(
                sorted(set(self.epilogue_anchors))))
        if self.prologue_anchors:
            edges.append("feeds " + "/".join(
                sorted(set(self.prologue_anchors))))
        tail = f" [{'; '.join(edges)}]" if edges else ""
        return (f"{rank}fusible chain of {self.n_eqns} memory-bound "
                f"eqn(s) ({prims}){sites} — est "
                f"{self.bytes_saved / 2**10:.1f} KiB HBM round-trips "
                f"saved ({self.time_saved_s * 1e6:.2f} us){tail}")

    def to_json(self) -> Dict[str, Any]:
        return {
            "code": self.code,
            "rank": self.rank,
            "where": self.where,
            "path": self.path,
            "primitives": list(self.primitives),
            "n_eqns": self.n_eqns,
            "count": self.count,
            "bytes_saved": float(self.bytes_saved),
            "time_saved_s": float(self.time_saved_s),
            "prologue_anchors": list(self.prologue_anchors),
            "epilogue_anchors": list(self.epilogue_anchors),
            "interior_anchors": self.interior_anchors,
            "suppressed": self.suppressed,
        }


@dataclasses.dataclass
class FusionReport:
    """Mined fusion candidates of one traced step."""

    name: str
    chip: ChipProfile
    candidates: List[FusionCandidate]   # ranked, F001–F003
    covered: List[FusionCandidate]      # F004 pallas leaves, unranked
    diagnostics: List[Diagnostic]       # through sort_diagnostics
    threshold_bytes: float = 0.0

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    def above_threshold(self) -> List[FusionCandidate]:
        """Unsuppressed non-F004 candidates at/over the bytes gate —
        what the CI fused-step stage requires to be EMPTY."""
        return [c for c in self.candidates
                if not c.suppressed and c.bytes_saved >= self.threshold_bytes]

    def summary(self) -> str:
        total = sum(c.bytes_saved for c in self.candidates
                    if not c.suppressed)
        n_sup = sum(1 for c in self.candidates if c.suppressed)
        sup = f", {n_sup} suppressed" if n_sup else ""
        return (f"[fusion] {self.name}: {len(self.candidates)} "
                f"candidate(s) ({len(self.above_threshold())} at/above "
                f"{self.threshold_bytes / 2**10:.0f} KiB{sup}), "
                f"{len(self.covered)} fused leaf group(s), est "
                f"{total / 2**20:.2f} MiB HBM round-trips recoverable "
                f"@ {self.chip.name}")

    def table(self, top: int = 8) -> str:
        rows = [f"{'rank':<6}{'code':<6}{'KiB saved':>10}{'us':>8}"
                f"{'sites':>6}  where"]
        for c in self.candidates[:top]:
            mark = " (suppressed)" if c.suppressed else ""
            rows.append(
                f"{('#' + str(c.rank)) if c.rank else '-':<6}{c.code:<6}"
                f"{c.bytes_saved / 2**10:>10.1f}"
                f"{c.time_saved_s * 1e6:>8.2f}{c.count:>6}  "
                f"{os.path.basename(c.where)}{mark}")
        for c in self.covered:
            rows.append(
                f"{'-':<6}{c.code:<6}{'-':>10}{'-':>8}{c.count:>6}  "
                f"{os.path.basename(c.where)} (already fused)")
        return "\n".join(rows)

    def to_json(self) -> Dict[str, Any]:
        """Machine-readable report (``lint_tpu --xray --fusion --json``)
        — diagnostics use the same shape as shardplan's ``to_json``."""
        return {
            "name": self.name,
            "chip": self.chip.name,
            "threshold_bytes": float(self.threshold_bytes),
            "candidates": [c.to_json() for c in self.candidates],
            "covered": [c.to_json() for c in self.covered],
            "n_above_threshold": len(self.above_threshold()),
            "diagnostics": [
                {"code": d.code, "severity": d.severity,
                 "message": d.message, "where": d.where}
                for d in self.diagnostics],
        }


# ---------------------------------------------------------------------------
# the mining walk
# ---------------------------------------------------------------------------

def _mine_level(jaxpr, mul: float, path: str, regions: List[_Region],
                leaves: List[Tuple[str, str, float]]):
    """Mine one open jaxpr level; recurse through non-transparent calls
    (scan trips multiply the savings).  ``leaves`` collects
    (kernel_name, where, priced_bytes) per pallas_call."""
    eqns = list(jaxpr.eqns)
    kinds: List[str] = []
    for eqn in eqns:
        kind = _eqn_kind(eqn)
        if kind == "call":
            subs = _sub_jaxprs(eqn)
            if len(subs) == 1 and _transparent(subs[0][0]):
                kind = "fusible"
            else:
                for inner, m in subs:
                    _mine_level(inner, mul * m,
                                f"{path}/{eqn.primitive.name}",
                                regions, leaves)
                kind = "barrier"
        elif kind == "fused_leaf":
            leaves.append((_pallas_kernel_name(eqn), _source_where(eqn),
                           mul * _eqn_bytes(eqn)))
        kinds.append(kind)

    free = set(v for v in tuple(jaxpr.invars) + tuple(jaxpr.constvars))
    escaping = set(v for v in jaxpr.outvars
                   if not isinstance(v, jax.core.Literal))
    producer: Dict[Any, int] = {}
    consumers: Dict[Any, List[int]] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            if not isinstance(v, jax.core.DropVar):
                producer[v] = i
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                consumers.setdefault(v, []).append(i)

    # chain growth to a fixpoint: a fusible producer joins its
    # consumers when every consumer is fusible and already in ONE
    # group (covers single-consumer edges and softmax-style diamonds)
    uf = _UnionFind()
    changed = True
    while changed:
        changed = False
        for v, prod in producer.items():
            if kinds[prod] != "fusible" or v in escaping:
                continue
            cons = sorted(set(consumers.get(v, ())))
            if not cons or any(kinds[c] != "fusible" for c in cons):
                continue
            roots = {uf.find(c) for c in cons}
            if len(roots) == 1:
                changed |= uf.union(prod, roots.pop())

    comp_eqns: Dict[int, List[int]] = {}
    for i, kind in enumerate(kinds):
        if kind == "fusible":
            comp_eqns.setdefault(uf.find(i), []).append(i)

    # per-component savings and anchor edges
    stats: Dict[int, Dict[str, Any]] = {}
    weight_anchor: Dict[int, bool] = {}
    for i, kind in enumerate(kinds):
        if kind == "anchor":
            weight_anchor[i] = any(
                v in free for v in eqns[i].invars
                if not isinstance(v, jax.core.Literal))
    for root, members in comp_eqns.items():
        mset = set(members)
        interior = 0.0
        n_leaf = 0
        prims: List[str] = []
        reduce_flag = False
        for i in members:
            leaf = _leaf_primitives(eqns[i])
            prims.extend(leaf)
            n_leaf += len(leaf)
            reduce_flag |= _contains_reduce(eqns[i])
            interior += _inner_interior_bytes_of_call(eqns[i])
            for v in eqns[i].outvars:
                if isinstance(v, jax.core.DropVar) or v in escaping:
                    continue
                cons = set(consumers.get(v, ()))
                if cons and cons <= mset:
                    interior += 2.0 * _var_bytes(v)
        prologue = 0.0
        prologue_to: List[int] = []
        epilogue = 0.0
        epilogue_from: List[int] = []
        seen_in: set = set()
        for i in members:
            for v in eqns[i].invars:
                if isinstance(v, jax.core.Literal) or v in seen_in:
                    continue
                seen_in.add(v)
                prod = producer.get(v)
                if prod is None or prod in mset:
                    continue
                if kinds[prod] == "anchor" and v not in escaping and \
                        set(consumers.get(v, ())) <= mset:
                    epilogue += 2.0 * _var_bytes(v)
                    epilogue_from.append(prod)
            for v in eqns[i].outvars:
                if isinstance(v, jax.core.DropVar) or v in escaping:
                    continue
                outside = sorted(set(consumers.get(v, ())) - mset)
                if outside and all(kinds[c] == "anchor" for c in outside):
                    prologue += (1.0 + len(outside)) * _var_bytes(v)
                    prologue_to.extend(outside)
        stats[root] = {
            "members": members, "interior": interior,
            "prologue": prologue, "prologue_to": prologue_to,
            "epilogue": epilogue, "epilogue_from": epilogue_from,
            "prims": prims, "n_leaf": n_leaf, "reduce": reduce_flag,
        }

    # region merge THROUGH data anchors (both operands locally
    # produced: attention score/context matmuls); weight anchors bound
    # regions like a real GEMM bounds an XLA fusion group
    ruf = _UnionFind()
    anchor_feeders: Dict[int, List[int]] = {}
    anchor_followers: Dict[int, List[int]] = {}
    for root, st in stats.items():
        for a in st["prologue_to"]:
            anchor_feeders.setdefault(a, []).append(root)
        for a in st["epilogue_from"]:
            anchor_followers.setdefault(a, []).append(root)
    for a, is_weight in weight_anchor.items():
        if is_weight:
            continue
        linked = anchor_feeders.get(a, []) + anchor_followers.get(a, [])
        for other in linked[1:]:
            ruf.union(linked[0], other)

    merged: Dict[int, List[int]] = {}
    for root in stats:
        merged.setdefault(ruf.find(root), []).append(root)

    for mroot, group in merged.items():
        interior = sum(stats[r]["interior"] for r in group)
        prologue = sum(stats[r]["prologue"] for r in group)
        epilogue = sum(stats[r]["epilogue"] for r in group)
        bytes_saved = (interior + prologue + epilogue) * mul
        if bytes_saved <= 0.0:
            continue
        members = sorted(i for r in group for i in stats[r]["members"])
        prims: List[str] = []
        for r in group:
            prims.extend(stats[r]["prims"])
        reduce_flag = any(stats[r]["reduce"] for r in group)
        # a data matmul fed by one of this region's chains AND followed
        # by another is interior: the region spans it (flash-attention
        # shape — both attention matmuls live inside the fused kernel)
        group_set = set(group)
        anchors_in = {
            a for a, is_weight in weight_anchor.items()
            if not is_weight
            and set(anchor_feeders.get(a, ())) & group_set
            and set(anchor_followers.get(a, ())) & group_set}
        prologue_names = sorted({
            eqns[a].primitive.name for r in group
            for a in stats[r]["prologue_to"] if a not in anchors_in})
        epilogue_names = sorted({
            eqns[a].primitive.name for r in group
            for a in stats[r]["epilogue_from"] if a not in anchors_in})
        if reduce_flag and (epilogue_names or anchors_in):
            code = "F003"
        elif reduce_flag and prologue_names:
            code = "F002"
        else:
            code = "F001"
        regions.append(_Region(
            code=code, where=_source_where(eqns[members[0]]), path=path,
            primitives=tuple(prims), n_eqns=len(prims),
            bytes_saved=bytes_saved,
            prologue_anchors=tuple(prologue_names),
            epilogue_anchors=tuple(epilogue_names),
            interior_anchors=len(anchors_in)))


def _inner_interior_bytes_of_call(eqn) -> float:
    """Interior bytes hidden inside a transparent call node (zero for a
    plain primitive)."""
    subs = _sub_jaxprs(eqn)
    if not subs:
        return 0.0
    return sum(_inner_interior_bytes(inner) for inner, _ in subs)


# ---------------------------------------------------------------------------
# suppression (the lint-tpu comment mechanism, applied to jaxpr sites)
# ---------------------------------------------------------------------------

_SUPPRESS_CACHE: Dict[str, Tuple[set, Dict[int, set]]] = {}


def _file_suppressions(path: str) -> Tuple[set, Dict[int, set]]:
    cached = _SUPPRESS_CACHE.get(path)
    if cached is not None:
        return cached
    from . import astlint

    try:
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
    except OSError:
        result: Tuple[set, Dict[int, set]] = (set(), {})
    else:
        result = astlint._suppressions(src)
    _SUPPRESS_CACHE[path] = result
    return result


def _is_suppressed(code: str, where: str) -> bool:
    fname, line = _where_key(where)
    if not fname or not os.path.isabs(fname):
        return False
    from . import astlint

    file_codes, line_codes = _file_suppressions(fname)
    return astlint._suppressed(code, line, file_codes, line_codes)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def mine_jaxpr(closed, *, name: str = "<jaxpr>", chip: str = "v5e",
               threshold_bytes: float = 0.0,
               suppress: bool = True) -> FusionReport:
    """Mine a ClosedJaxpr for fusion candidates (see module docstring).

    ``threshold_bytes`` sets the severity split: candidates saving at
    least this much are WARNING (and count for ``above_threshold`` /
    the CI gate), smaller ones are INFO.  ``suppress=False`` keeps
    lint-tpu-suppressed candidates in the ranking (they are marked but
    never WARNING)."""
    profile = CHIPS[chip] if isinstance(chip, str) else chip
    regions: List[_Region] = []
    leaves: List[Tuple[str, str, float]] = []
    _mine_level(closed.jaxpr, 1.0, "", regions, leaves)

    # group structurally identical regions (same code, source site and
    # primitive signature — one model line traced per layer)
    grouped: Dict[Tuple[str, str, Tuple[str, ...]], FusionCandidate] = {}
    for r in regions:
        key = (r.code, r.where, tuple(sorted(r.primitives)))
        cand = grouped.get(key)
        if cand is None:
            grouped[key] = FusionCandidate(
                code=r.code, where=r.where, path=r.path,
                primitives=r.primitives, n_eqns=r.n_eqns, count=1,
                bytes_saved=r.bytes_saved,
                time_saved_s=r.bytes_saved / profile.hbm_bandwidth,
                prologue_anchors=r.prologue_anchors,
                epilogue_anchors=r.epilogue_anchors,
                interior_anchors=r.interior_anchors)
        else:
            cand.count += 1
            cand.bytes_saved += r.bytes_saved
            cand.time_saved_s = cand.bytes_saved / profile.hbm_bandwidth

    candidates = list(grouped.values())
    for c in candidates:
        c.suppressed = bool(suppress) and _is_suppressed(c.code, c.where)
    # ranking: bytes-saved desc, ties by (file, line); suppressed
    # candidates drop out of the ranking (and the exit-code gate)
    candidates.sort(key=lambda c: (-c.bytes_saved,) + _where_key(c.where))
    rank = 0
    for c in candidates:
        if c.suppressed:
            c.rank = None
        else:
            rank += 1
            c.rank = rank

    covered_by: Dict[Tuple[str, str], FusionCandidate] = {}
    for kernel, where, bytes_priced in leaves:
        key = (kernel, where)
        cand = covered_by.get(key)
        if cand is None:
            covered_by[key] = FusionCandidate(
                code="F004", where=where, path="", primitives=(kernel,),
                n_eqns=1, count=1, bytes_saved=0.0, time_saved_s=0.0,
                prologue_anchors=(), epilogue_anchors=(),
                interior_anchors=0)
        else:
            cand.count += 1
    covered = sorted(covered_by.values(),
                     key=lambda c: (c.primitives[0],) + _where_key(c.where))

    diags: List[Diagnostic] = []
    for c in candidates:
        if c.suppressed:
            continue
        sev = WARNING if c.bytes_saved >= threshold_bytes else INFO
        diags.append(Diagnostic(c.code, sev, c.describe(), c.where))
    for c in covered:
        diags.append(Diagnostic(
            "F004", INFO,
            f"already fused: pallas kernel '{c.primitives[0]}' "
            f"x{c.count} (priced via kernels.costs) — excluded from "
            "ranking", c.where))
    return FusionReport(
        name=name, chip=profile, candidates=candidates, covered=covered,
        diagnostics=sort_diagnostics(diags),
        threshold_bytes=float(threshold_bytes))


def mine(step, abstract_args: Sequence[Any], *,
         name: Optional[str] = None, chip: str = "v5e",
         threshold_bytes: float = 0.0,
         suppress: bool = True) -> FusionReport:
    """Trace ``step`` on abstract args (xray.analyze's convention) and
    mine the jaxpr."""
    fn = step
    if hasattr(fn, "_fn") and hasattr(fn, "compiles"):
        fn = fn._fn
    args = [jax.tree_util.tree_map(_as_abstract, a,
                                   is_leaf=lambda x: hasattr(x, "_value"))
            for a in abstract_args]
    closed = jax.make_jaxpr(fn)(*args)
    return mine_jaxpr(closed,
                      name=name or getattr(step, "__name__", "<step>"),
                      chip=chip, threshold_bytes=threshold_bytes,
                      suppress=suppress)


#: default CI gate: a fused serving step must leave nothing this big
#: unfused.  Calibrated on the tiny audit model: the kernel-scale
#: attention regions mine at ~1.6 MiB per step, while the largest
#: chain the fused steps legitimately leave behind (the chunk RoPE
#: gather chain) is ~340 KiB — the gate sits between the two
DEFAULT_THRESHOLD_BYTES = 512 * 1024


def audit_fusion(*, chip: str = "cpu",
                 threshold_bytes: float = DEFAULT_THRESHOLD_BYTES,
                 fused: bool = False,
                 suppress: bool = True) -> List[FusionReport]:
    """Mine the registered serving steps on the tiny audit model
    (mirrors ``xray.audit_default_steps``'s serving half) — the
    ``lint_tpu --xray --fusion`` / CI entry point.

    ``fused=True`` additionally mines the FUSED decode/prefill steps
    traced under ``force_pallas_interpret()`` so the programs carry the
    real ``pallas_call`` leaves on any backend: the hand-fused chains
    must come back as F004 coverage, not as candidates — CI gates that
    ``above_threshold()`` is empty for those reports."""
    import paddle_tpu as paddle
    from ..kernels.fusion import force_pallas_interpret
    from ..models import LlamaConfig, LlamaForCausalLM
    from ..models.generation import (make_chunked_prefill_step,
                                     make_paged_decode_step)
    from .xray import _serving_abstract_args

    paddle.seed(0)
    net = LlamaForCausalLM(LlamaConfig.tiny())
    net.eval()
    decode_args, prefill_args = _serving_abstract_args(
        net, batch=4, num_blocks=32, block_size=8,
        max_blocks_per_seq=8, chunk_tokens=32)
    reports = [
        mine(make_paged_decode_step(net, fused=False), decode_args,
             name="serving::decode_step", chip=chip,
             threshold_bytes=threshold_bytes, suppress=suppress),
        mine(make_chunked_prefill_step(net, fused=False), prefill_args,
             name="serving::prefill_step", chip=chip,
             threshold_bytes=threshold_bytes, suppress=suppress),
    ]
    if fused:
        with force_pallas_interpret():
            reports.append(mine(
                make_paged_decode_step(net, fused=True), decode_args,
                name="serving::decode_step[fused]", chip=chip,
                threshold_bytes=threshold_bytes, suppress=suppress))
            reports.append(mine(
                make_chunked_prefill_step(net, fused=True), prefill_args,
                name="serving::prefill_step[fused]", chip=chip,
                threshold_bytes=threshold_bytes, suppress=suppress))
    return reports
