"""Jaxpr-level program X-ray: static cost, memory, donation, and
sharding-readiness analysis.

The AST scanners in :mod:`paddle_tpu.analysis.hazards` see *source*; this
module sees the *traced program*.  "Operator Fusion in XLA: Analysis and
Evaluation" (PAPERS.md) shows fusion/TPU wins are governed by the
arithmetic intensity of the ops around each kernel, and the remaining
ROADMAP items (mesh sharding, fused paged attention) all need per-op
FLOP/byte facts the AST cannot produce.  So: trace any registered step
to a jaxpr and walk it.

What :func:`analyze` produces (a :class:`ProgramReport`):

- **per-primitive FLOP/byte cost model** — dot_general from its
  contraction dims, conv from kernel volume, gathers/scatters and
  elementwise from element counts; bytes are operand+result sizes.
- **roofline classification** — each primitive's aggregate arithmetic
  intensity (FLOP/byte) against the chip's ridge point
  (peak FLOPs / HBM bandwidth): ``compute``- or ``memory``-bound.
- **peak-live-HBM** — a linear-scan liveness walk over the jaxpr
  (invars/constvars live from entry to last use, eqn outvars from
  definition to last use, program outputs through the end; call-like
  eqns contribute their inner peak as a transient), gated against a
  configurable per-chip HBM budget (**H110** ERROR when exceeded).

Jaxpr-level hazards (Diagnostic codes continue hazards.py's space):

- **H108 missing-donation** (WARNING) — a large undonated input whose
  shape/dtype matches an output: XLA must double-buffer it, costing its
  full size in HBM.  Train steps donate state via ``jit.to_static``
  (donate_argnums=(0,)); serving steps returning fresh pools show up
  here by design until pool donation lands.
- **H109 host round-trip in compiled region** (ERROR; ``debug_callback``
  WARNING) — ``pure_callback``/``io_callback``/``outside_call``
  primitives found ANYWHERE in the jaxpr: a device→host→device round
  trip per execution that no amount of fusion can hide.  This is the
  traced-program superset of AST H102/H106 — it sees through helper
  indirection the source scan cannot.
- **H103 f64 in traced program** (ERROR) — an equation producing
  float64/complex128: software-emulated on TPU (same code as the AST
  scan; this half catches dtypes built out of sight of the source).

Sharding readiness (S201–S204, :func:`check_sharding_readiness`):
validates a ``{param_role: PartitionSpec}`` layout dict against an
abstract mesh ``{axis: size}`` and the parameter shapes — unknown mesh
axis (S201), duplicate axis within one spec (S202), spec rank exceeding
the param rank (S203), dimension not divisible by the product of its
mesh axes (S204) — so the upcoming ``paddle_tpu.distributed`` mesh PR
lands against a verifier that already exists.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .verifier import ERROR, WARNING, Diagnostic

__all__ = [
    "ChipProfile",
    "CHIPS",
    "OpCost",
    "ProgramReport",
    "analyze",
    "analyze_train_step",
    "audit_default_steps",
    "check_sharding_readiness",
    "estimate_collective_time",
    "estimate_compute_time",
    "export_report_gauges",
]


# ---------------------------------------------------------------------------
# chip roofline profiles
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChipProfile:
    """Peak compute / memory figures for the roofline ridge point.

    Public per-chip specs (bf16 peak, HBM bandwidth, HBM capacity);
    ``cpu`` is a deliberately modest dev-box stand-in so CPU CI still
    exercises the classification logic.
    """

    name: str
    peak_flops: float        # FLOP/s (bf16)
    hbm_bandwidth: float     # bytes/s
    hbm_bytes: int           # capacity per chip
    ici_bandwidth: float = 1e11   # bytes/s per chip over the interconnect
    ici_latency: float = 1e-6     # per-collective launch latency, seconds
    dcn_bandwidth: float = 6.25e9  # bytes/s per chip over the data-center net
    dcn_latency: float = 1e-5      # per-collective DCN launch latency, seconds

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (FLOP/byte) where the roofline bends."""
        return self.peak_flops / self.hbm_bandwidth


# ICI figures are aggregate per-chip interconnect bandwidth from the
# public Cloud TPU system-architecture pages: v4 has 6 links x 50 GB/s
# (3D torus, 2400 Gbps aggregate); v5e 4 links x 400 Gbps (1600 Gbps,
# 2D torus); v5p 4800 Gbps over 6 links (3D torus); v6e (Trillium)
# 3584 Gbps over 4 links.  Latency is the one-hop launch overhead, order
# 1 us on real ICI.  "cpu" is loopback shared memory on the dev box —
# fast and near-zero-latency so CPU CI classifies the tiny model as
# compute-heavy the way a real topology-free single host would.
#
# DCN figures are the per-chip share of the host NIC from the public
# multislice / system-architecture pages: v4 and v5e hosts carry
# 100–200 Gbps NICs over 4 chips, v5p and v6e (Trillium) quote 400 Gbps
# per host.  DCN latency is cross-host (order 10 us), an order of
# magnitude above one ICI hop — the multi-host planner prices DCN edges
# from these instead of needing another CHIPS schema change.  "cpu"'s
# DCN, like its ICI, is loopback: an emulated multi-host topology on
# one dev box crosses no real NIC, and CPU CI must classify the tiny
# model the way the real chips would (compute-bound when the layout is
# sane) while keeping DCN strictly slower than ICI so the level split
# stays visible in every report.
CHIPS: Dict[str, ChipProfile] = {
    "v4": ChipProfile("v4", 275e12, 1228e9, 32 << 30, 300e9, 1e-6,
                      6.25e9, 1e-5),
    "v5e": ChipProfile("v5e", 197e12, 819e9, 16 << 30, 200e9, 1e-6,
                       3.125e9, 1e-5),
    "v5p": ChipProfile("v5p", 459e12, 2765e9, 95 << 30, 600e9, 1e-6,
                       12.5e9, 1e-5),
    "v6e": ChipProfile("v6e", 918e12, 1640e9, 32 << 30, 448e9, 1e-6,
                       12.5e9, 1e-5),
    "cpu": ChipProfile("cpu", 5e11, 50e9, 8 << 30, 200e9, 0.0,
                       25e9, 2e-7),
}


def estimate_compute_time(flops: float, bytes_moved: float,
                          chip: ChipProfile) -> float:
    """Roofline step-time estimate: the max of the compute-bound and
    memory-bound times.  Shared by the xray summary and shardplan's S207
    so compute-vs-comm classification is consistent between the two."""
    return max(flops / chip.peak_flops,
               bytes_moved / chip.hbm_bandwidth)


def estimate_collective_time(bytes_on_wire: float,
                             chip: ChipProfile,
                             level: str = "ici") -> float:
    """Time for one collective that puts ``bytes_on_wire`` on each
    chip's links (ring-formula bytes, computed by the caller).
    ``level`` selects the link profile: ``"ici"`` (intra-host, the
    default — single-host plans never say otherwise) or ``"dcn"``
    (cross-host phases of a hierarchically decomposed collective)."""
    if level == "dcn":
        return bytes_on_wire / chip.dcn_bandwidth + chip.dcn_latency
    return bytes_on_wire / chip.ici_bandwidth + chip.ici_latency


# ---------------------------------------------------------------------------
# sizes and helpers
# ---------------------------------------------------------------------------

def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0  # tokens / effects / abstract non-arrays
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except TypeError:
        return 0


def _var_bytes(v) -> int:
    if isinstance(v, jax.core.Literal):
        return 0  # inlined scalar constants
    return _aval_bytes(v.aval)


def _elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64))


# call-like primitives and where their sub-jaxprs live; validated
# against jax 0.4.x primitive params (pjit carries a ClosedJaxpr,
# custom_* carry call_jaxpr, scan multiplies by its trip count)
_TRANSCENDENTAL = {
    "exp", "log", "log1p", "expm1", "tanh", "sin", "cos", "tan",
    "erf", "erfc", "erf_inv", "logistic", "pow", "cbrt", "atan2",
    "digamma", "lgamma",
}
# pure data movement: 0 FLOPs, bytes still counted
_MOVEMENT = {
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "rev",
    "slice", "dynamic_slice", "dynamic_update_slice", "gather",
    "scatter", "concatenate", "pad", "iota", "copy", "device_put",
    "convert_element_type", "bitcast_convert_type", "select_n",
    "stop_gradient", "split", "expand_dims",
}
_CALLBACKS = {
    "pure_callback": ERROR,
    "io_callback": ERROR,
    "outside_call": ERROR,
    "debug_callback": WARNING,
}


def _sub_jaxprs(eqn):
    """Yield (inner open jaxpr, static trip multiplier) for call-like
    equations.  ``cond`` yields every branch (cost walk takes the max;
    liveness takes the max transient)."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "pallas_call":
        # a priced LEAF, not a call: the kernel body jaxpr under
        # params["jaxpr"] is per-BLOCK code — walking it would charge
        # one grid cell as if it were the whole op.  The kernel's cost
        # comes from the kernels.costs registry (or its own
        # CostEstimate) in _eqn_flops/_eqn_bytes.
        return []
    if name == "cond":
        return [(b.jaxpr, 1) for b in params["branches"]]
    if name == "while":
        return [(params["cond_jaxpr"].jaxpr, 1),
                (params["body_jaxpr"].jaxpr, 1)]
    if name == "scan":
        return [(params["jaxpr"].jaxpr, int(params.get("length", 1)))]
    # custom_vjp_call_jaxpr keeps its primal body under ``fun_jaxpr``
    # (custom_jvp uses call_jaxpr) — without it the analyzers are blind
    # to anything wrapped for a hand-written backward, e.g. moe_dispatch
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        inner = params.get(key)
        if inner is not None:
            inner = getattr(inner, "jaxpr", inner)  # Closed -> open
            return [(inner, 1)]
    return []


def _is_call_like(eqn) -> bool:
    return bool(_sub_jaxprs(eqn))


# ---------------------------------------------------------------------------
# FLOP model
# ---------------------------------------------------------------------------

def _dot_flops(eqn) -> float:
    ((lc, rc), (lb, _rb)) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = int(np.prod([lhs[i] for i in lb], dtype=np.int64)) if lb else 1
    k = int(np.prod([lhs[i] for i in lc], dtype=np.int64)) if lc else 1
    m = int(np.prod([lhs[i] for i in range(len(lhs))
                     if i not in tuple(lc) + tuple(lb)], dtype=np.int64))
    n = int(np.prod([rhs[i] for i in range(len(rhs))
                     if i not in tuple(rc) + tuple(_rb)], dtype=np.int64))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dn = eqn.params.get("dimension_numbers")
    out_feature_dim = dn.rhs_spec[0] if dn is not None else 0
    kernel_elems = _elems(rhs)
    out_ch = rhs.shape[out_feature_dim] if rhs.shape else 1
    # per output element: one MAC per kernel tap feeding it
    per_out = kernel_elems / max(1, out_ch)
    return 2.0 * _elems(out) * per_out


def _pallas_kernel_name(eqn) -> str:
    """The kernel's ``name=`` as it appears in the cost registry."""
    info = eqn.params.get("name_and_src_info")
    name = getattr(info, "name", None) or eqn.params.get("name")
    return str(name) if name else "unnamed"


def _pallas_cost(eqn):
    """Registered KernelCost for a pallas_call eqn, else the kernel's
    own CostEstimate param, else None (generic pricing)."""
    from ..kernels.costs import price_eqn_avals

    in_avals = [(tuple(v.aval.shape), str(v.aval.dtype))
                for v in eqn.invars
                if not isinstance(v, jax.core.Literal)]
    out_avals = [(tuple(v.aval.shape), str(v.aval.dtype))
                 for v in eqn.outvars]
    cost = price_eqn_avals(_pallas_kernel_name(eqn), in_avals, out_avals)
    if cost is not None:
        return cost
    est = eqn.params.get("cost_estimate")
    if est is not None and getattr(est, "bytes_accessed", 0):
        return est
    return None


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "pallas_call":
        cost = _pallas_cost(eqn)
        if cost is not None:
            return float(cost.flops)
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _MOVEMENT:
        return 0.0
    in_elems = max((_elems(v.aval) for v in eqn.invars
                    if not isinstance(v, jax.core.Literal)), default=0)
    out_elems = max((_elems(v.aval) for v in eqn.outvars), default=0)
    if name in ("sort", "top_k"):
        n = max(in_elems, 1)
        return n * max(1.0, math.log2(n))
    if name.startswith(("reduce_", "cum", "arg")):
        return float(in_elems)
    if name in _TRANSCENDENTAL:
        # several fused hardware ops per element; a fixed weight keeps
        # the model honest about transcendental-heavy regions without
        # pretending to cycle accuracy
        return 10.0 * float(max(in_elems, out_elems))
    return float(max(in_elems, out_elems))


def _eqn_bytes(eqn) -> float:
    if eqn.primitive.name == "pallas_call":
        cost = _pallas_cost(eqn)
        if cost is not None:
            # the registered/declared traffic model — e.g. paged decode
            # reads the pool THROUGH the block table, so its bytes are
            # the gathered context, not the whole pool operand
            return float(cost.bytes_accessed)
    return float(sum(_var_bytes(v) for v in eqn.invars)
                 + sum(_var_bytes(v) for v in eqn.outvars))


# ---------------------------------------------------------------------------
# recursive cost walk
# ---------------------------------------------------------------------------

def _collect_costs(jaxpr, mul: float, acc: Dict[str, List[float]]):
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            if eqn.primitive.name == "cond":
                # branches are exclusive: charge the most expensive one
                best, best_cost = None, -1.0
                for inner, m in subs:
                    trial: Dict[str, List[float]] = {}
                    _collect_costs(inner, mul * m, trial)
                    cost = sum(v[0] for v in trial.values())
                    if cost > best_cost:
                        best, best_cost = trial, cost
                for k, (f, b, c) in (best or {}).items():
                    cur = acc.setdefault(k, [0.0, 0.0, 0.0])
                    cur[0] += f
                    cur[1] += b
                    cur[2] += c
            else:
                for inner, m in subs:
                    _collect_costs(inner, mul * m, acc)
            continue
        key = eqn.primitive.name
        if key == "pallas_call":
            # per-kernel row so the fused steps read as their kernels,
            # not one anonymous pallas bucket
            key = f"pallas_call:{_pallas_kernel_name(eqn)}"
        cur = acc.setdefault(key, [0.0, 0.0, 0.0])
        cur[0] += mul * _eqn_flops(eqn)
        cur[1] += mul * _eqn_bytes(eqn)
        cur[2] += mul


def _count_eqns(jaxpr) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        n += sum(_count_eqns(inner) for inner, _ in subs) if subs else 1
    return n


# ---------------------------------------------------------------------------
# liveness walk (peak HBM)
# ---------------------------------------------------------------------------

def _var_dtype(v) -> str:
    dt = getattr(getattr(v, "aval", None), "dtype", None)
    return str(dt) if dt is not None else "opaque"


def _peak_live_by_dtype(jaxpr, var_bytes=_var_bytes
                        ) -> Tuple[int, Dict[str, int]]:
    """Linear-scan liveness over one open jaxpr: a var is live from its
    definition (entry for invars/constvars) to its last use (program end
    for outputs).  Call-like eqns add ``inner_peak - boundary`` as a
    transient — the inner program's scratch beyond what the caller
    already accounts for at the call boundary.

    Returns ``(peak_bytes, {dtype: bytes held at the peak})`` — the
    breakdown is a snapshot of the live set when the peak is reached
    (call-like transients attributed by the inner program's own dtype
    mix beyond the boundary), so int8/fp8 KV or weight buffers show up
    as their own line instead of vanishing into one total.

    ``var_bytes`` maps a jaxpr var (or Literal) to its byte size;
    shardplan passes a shard-aware callback that divides each buffer by
    its shard count, turning this same walk into *per-chip* peak HBM."""
    n = len(jaxpr.eqns)
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not isinstance(v, jax.core.Literal):
            last_use[v] = n  # live through the end
    live: Dict[Any, int] = {}
    by_dtype: Dict[str, int] = {}

    def _add(v):
        b = var_bytes(v)
        live[v] = b
        if b:
            dt = _var_dtype(v)
            by_dtype[dt] = by_dtype.get(dt, 0) + b
        return b

    def _drop(v):
        b = live.pop(v)
        if b:
            dt = _var_dtype(v)
            rem = by_dtype.get(dt, 0) - b
            if rem > 0:
                by_dtype[dt] = rem
            else:
                by_dtype.pop(dt, None)
        return b

    for v in tuple(jaxpr.invars) + tuple(jaxpr.constvars):
        _add(v)
    current = sum(live.values())
    peak = current
    snap = dict(by_dtype)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            if v not in live:
                current += _add(v)
        transient = 0
        extra_bd: Dict[str, int] = {}
        subs = _sub_jaxprs(eqn)
        if subs:
            boundary = (sum(var_bytes(v) for v in eqn.invars)
                        + sum(var_bytes(v) for v in eqn.outvars))
            inner_peak, inner_bd = -1, {}
            for inner, _ in subs:
                ip, ibd = _peak_live_by_dtype(inner, var_bytes)
                if ip > inner_peak:
                    inner_peak, inner_bd = ip, ibd
            transient = max(0, inner_peak - boundary)
            if transient > 0:
                # attribute the scratch beyond the boundary by the inner
                # program's dtype mix (minus what the boundary already
                # holds per dtype), rescaled to sum to the transient
                bound_bd: Dict[str, int] = {}
                for v in tuple(eqn.invars) + tuple(eqn.outvars):
                    b = var_bytes(v)
                    if b:
                        dt = _var_dtype(v)
                        bound_bd[dt] = bound_bd.get(dt, 0) + b
                extra = {dt: max(0, b - bound_bd.get(dt, 0))
                         for dt, b in inner_bd.items()}
                s = sum(extra.values())
                if s > 0:
                    extra_bd = {dt: int(round(b * transient / s))
                                for dt, b in extra.items() if b}
                else:
                    extra_bd = {"opaque": transient}
        if current + transient > peak:
            peak = current + transient
            snap = dict(by_dtype)
            for dt, b in extra_bd.items():
                snap[dt] = snap.get(dt, 0) + b
        for v in tuple(eqn.invars) + tuple(eqn.outvars):
            if isinstance(v, jax.core.Literal):
                continue
            if last_use.get(v, -1) <= i and v in live:
                current -= _drop(v)
    return peak, snap


def _peak_live_bytes(jaxpr, var_bytes=_var_bytes) -> int:
    """Peak-only view of :func:`_peak_live_by_dtype` (same walk)."""
    return _peak_live_by_dtype(jaxpr, var_bytes)[0]


# ---------------------------------------------------------------------------
# hazards over the traced program
# ---------------------------------------------------------------------------

def _scan_callbacks(jaxpr, diags: List[Diagnostic], where: str):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _CALLBACKS:
            diags.append(Diagnostic(
                "H109", _CALLBACKS[name],
                f"'{name}' inside the compiled program: a device→host→"
                "device round trip EVERY execution — XLA cannot fuse or "
                "overlap across it.  Hoist the host work outside the "
                "step (this is the traced-program form of H102/H106; it "
                "sees through helper indirection)", where))
        for inner, _ in _sub_jaxprs(eqn):
            _scan_callbacks(inner, diags, where)


def _scan_f64(jaxpr, diags: List[Diagnostic], where: str):
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            for inner, _ in subs:
                _scan_f64(inner, diags, where)
            continue
        for v in eqn.outvars:
            dt = getattr(v.aval, "dtype", None)
            if dt is not None and str(dt) in ("float64", "complex128"):
                diags.append(Diagnostic(
                    "H103", ERROR,
                    f"'{eqn.primitive.name}' produces {dt} inside the "
                    "traced program: TPUs have no native f64 — this op "
                    "(and everything fused with it) runs software-"
                    "emulated", where))


def _scan_donation(jaxpr, donated: Sequence[bool], min_bytes: int,
                   diags: List[Diagnostic], where: str):
    """H108: an undonated input whose shape/dtype matches an output that
    is not the input itself — XLA must keep both alive (double-buffered
    HBM for its full size)."""
    out_pool: List[Any] = [v for v in jaxpr.outvars
                           if not isinstance(v, jax.core.Literal)]
    for i, v in enumerate(jaxpr.invars):
        if i < len(donated) and donated[i]:
            continue
        size = _var_bytes(v)
        if size < min_bytes:
            continue
        aval = v.aval
        match = None
        for o in out_pool:
            if o is v:
                continue  # passed straight through: aliasing is free
            if (getattr(o.aval, "shape", None) == aval.shape
                    and getattr(o.aval, "dtype", None) == aval.dtype):
                match = o
                break
        if match is not None:
            out_pool.remove(match)
            diags.append(Diagnostic(
                "H108", WARNING,
                f"input {i} ({tuple(aval.shape)} {aval.dtype}, "
                f"{size / 2**20:.1f} MiB) is not donated but an output "
                "of identical shape/dtype exists — XLA double-buffers "
                "it; donate the argument (jax.jit donate_argnums / "
                "jit.to_static state donation) so the output reuses the "
                "input's HBM", where))


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OpCost:
    """Aggregate cost of one primitive across the whole program."""

    primitive: str
    count: int
    flops: float
    bytes: float

    @property
    def intensity(self) -> float:
        return self.flops / self.bytes if self.bytes else 0.0

    def bound(self, chip: ChipProfile) -> str:
        return "compute" if self.intensity >= chip.ridge else "memory"


@dataclasses.dataclass
class ProgramReport:
    """Static X-ray of one traced step (see module docstring)."""

    name: str
    chip: ChipProfile
    flops: float
    bytes: float
    peak_hbm_bytes: int
    ops: List[OpCost]
    n_eqns: int
    donated: Tuple[bool, ...]
    hazards: List[Diagnostic]
    hbm_budget_bytes: Optional[int] = None
    # dtype -> bytes held when the liveness walk hits its peak; sums to
    # peak_hbm_bytes (groundwork for int8/fp8 KV accounting)
    peak_hbm_by_dtype: Dict[str, int] = dataclasses.field(
        default_factory=dict)

    @property
    def arithmetic_intensity(self) -> float:
        return self.flops / self.bytes if self.bytes else 0.0

    @property
    def compute_time_s(self) -> float:
        """Roofline single-chip step-time estimate (shared formula with
        shardplan's comm-vs-compute classification)."""
        return estimate_compute_time(self.flops, self.bytes, self.chip)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.hazards if d.severity == ERROR]

    def table(self, top: int = 12) -> str:
        """Roofline table: primitive, calls, MFLOPs, MiB, FLOP/B, bound
        (the README "Program X-ray" section documents the columns)."""
        rows = [f"{'primitive':<24}{'calls':>7}{'MFLOPs':>10}"
                f"{'MiB':>9}{'FLOP/B':>9}  bound"]
        for op in self.ops[:top]:
            rows.append(
                f"{op.primitive:<24}{op.count:>7.0f}"
                f"{op.flops / 1e6:>10.2f}{op.bytes / 2**20:>9.2f}"
                f"{op.intensity:>9.2f}  {op.bound(self.chip)}")
        return "\n".join(rows)

    def to_json(self) -> Dict[str, Any]:
        """Machine-readable report (``lint_tpu --xray --json``) —
        diagnostics use the same shape as shardplan's ``to_json``."""
        return {
            "name": self.name,
            "chip": self.chip.name,
            "flops": float(self.flops),
            "bytes": float(self.bytes),
            "arithmetic_intensity": float(self.arithmetic_intensity),
            "compute_time_s": float(self.compute_time_s),
            "peak_hbm_bytes": int(self.peak_hbm_bytes),
            "peak_hbm_by_dtype": {k: int(v) for k, v in
                                  self.peak_hbm_by_dtype.items()},
            "hbm_budget_bytes": (int(self.hbm_budget_bytes)
                                 if self.hbm_budget_bytes else None),
            "n_eqns": int(self.n_eqns),
            "donated": list(self.donated),
            "ops": [
                {"primitive": op.primitive, "count": int(op.count),
                 "flops": float(op.flops), "bytes": float(op.bytes),
                 "intensity": float(op.intensity),
                 "bound": op.bound(self.chip)}
                for op in self.ops],
            "diagnostics": [
                {"code": d.code, "severity": d.severity,
                 "message": d.message, "where": d.where}
                for d in self.hazards],
        }

    def summary(self) -> str:
        budget = (f" / budget {self.hbm_budget_bytes / 2**30:.2f} GiB"
                  if self.hbm_budget_bytes else "")
        return (f"[xray] {self.name}: {self.flops / 1e9:.3f} GFLOP, "
                f"{self.bytes / 2**30:.3f} GiB moved, intensity "
                f"{self.arithmetic_intensity:.2f} FLOP/B "
                f"(ridge {self.chip.ridge:.1f} @ {self.chip.name}, "
                f"ici {self.chip.ici_bandwidth / 1e9:.0f} GB/s), "
                f"est step {self.compute_time_s * 1e3:.3f} ms, "
                f"peak HBM {self.peak_hbm_bytes / 2**20:.2f} MiB{budget}, "
                f"{self.n_eqns} eqns, {len(self.hazards)} hazard(s)")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _as_abstract(x):
    v = getattr(x, "_value", x)  # paddle Tensor -> backing array
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return jax.ShapeDtypeStruct(np.shape(v), np.dtype(v.dtype))
    return v


def _donated_mask(closed, abstract_args, donate_argnums) -> Tuple[bool, ...]:
    n_in = len(closed.jaxpr.invars)
    mask = [False] * n_in
    if donate_argnums:
        donate = set(donate_argnums)
        pos = 0
        for i, a in enumerate(abstract_args):
            leaves = len(jax.tree_util.tree_leaves(a))
            if i in donate:
                for j in range(pos, min(pos + leaves, n_in)):
                    mask[j] = True
            pos += leaves
    # a jitted step traces to ONE pjit eqn that carries the real
    # donated_invars — trust it over the caller's donate_argnums
    eqns = closed.jaxpr.eqns
    if len(eqns) == 1 and eqns[0].primitive.name == "pjit":
        flags = eqns[0].params.get("donated_invars")
        if flags is not None:
            by_var = {v: f for v, f in zip(eqns[0].invars, flags)
                      if not isinstance(v, jax.core.Literal)}
            mask = [by_var.get(v, False) for v in closed.jaxpr.invars]
    return tuple(mask)


def analyze(step, abstract_args: Sequence[Any], *,
            name: Optional[str] = None,
            donate_argnums: Sequence[int] = (),
            chip: str = "v5e",
            hbm_budget_bytes: Optional[int] = None,
            min_donation_bytes: int = 1 << 20) -> ProgramReport:
    """X-ray ``step`` (a jitted or plain function) called with
    ``abstract_args`` (ShapeDtypeStructs, arrays, Tensors, or pytrees of
    them — values are never computed, only shapes).  Returns a
    :class:`ProgramReport`; raises nothing on hazards (callers gate on
    ``report.errors()``)."""
    fn = step
    if hasattr(fn, "_fn") and hasattr(fn, "compiles"):
        fn = fn._fn  # observability track_compiles/warn_on_retrace wrapper
    args = [jax.tree_util.tree_map(_as_abstract, a,
                                   is_leaf=lambda x: hasattr(x, "_value"))
            for a in abstract_args]
    closed = jax.make_jaxpr(fn)(*args)
    donated = _donated_mask(closed, args, donate_argnums)
    return analyze_jaxpr(
        closed, donated=donated,
        name=name or getattr(step, "__name__", "<step>"), chip=chip,
        hbm_budget_bytes=hbm_budget_bytes,
        min_donation_bytes=min_donation_bytes)


def analyze_jaxpr(closed, *, donated: Sequence[bool] = (),
                  name: str = "<jaxpr>", chip: str = "v5e",
                  hbm_budget_bytes: Optional[int] = None,
                  min_donation_bytes: int = 1 << 20) -> ProgramReport:
    """The jaxpr-in half of :func:`analyze` — use when the trace came
    from elsewhere (``StaticFunction.trace_jaxpr``, ``jax.make_jaxpr``)."""
    profile = CHIPS[chip] if isinstance(chip, str) else chip
    jaxpr = closed.jaxpr
    acc: Dict[str, List[float]] = {}
    _collect_costs(jaxpr, 1.0, acc)
    ops = sorted((OpCost(k, int(c), f, b) for k, (f, b, c) in acc.items()),
                 key=lambda o: (-o.flops, -o.bytes, o.primitive))
    diags: List[Diagnostic] = []
    where = f"xray:{name}"
    _scan_callbacks(jaxpr, diags, where)
    _scan_f64(jaxpr, diags, where)
    donated = tuple(donated) or (False,) * len(jaxpr.invars)
    _scan_donation(jaxpr, donated, min_donation_bytes, diags, where)
    peak, peak_by_dtype = _peak_live_by_dtype(jaxpr)
    budget = hbm_budget_bytes
    if budget is not None and peak > budget:
        diags.append(Diagnostic(
            "H110", ERROR,
            f"peak live HBM {peak / 2**30:.3f} GiB exceeds the "
            f"{budget / 2**30:.3f} GiB budget — this program cannot fit "
            "the configured chip; shrink the batch/model, enable remat, "
            "or shard before deploying", where))
    from .hazards import sort_diagnostics

    return ProgramReport(
        name=name, chip=profile,
        flops=sum(o.flops for o in ops),
        bytes=sum(o.bytes for o in ops),
        peak_hbm_bytes=peak, ops=ops, n_eqns=_count_eqns(jaxpr),
        donated=donated, hazards=sort_diagnostics(diags),
        hbm_budget_bytes=budget, peak_hbm_by_dtype=peak_by_dtype)


def analyze_train_step(step_fn, inputs, labels, *,
                       name: str = "hapi::train_step", chip: str = "v5e",
                       hbm_budget_bytes: Optional[int] = None,
                       min_donation_bytes: int = 1 << 20) -> ProgramReport:
    """X-ray a ``jit.to_static`` train step (or the
    ``observability.track_compiles`` wrapper around one) on sample
    ``inputs``/``labels``.  Uses ``StaticFunction.trace_jaxpr``, which
    donates the state leaves exactly like the real call path."""
    sfn = getattr(step_fn, "_fn", step_fn)   # TrackedFunction -> static fn
    closed, donated = sfn.trace_jaxpr(inputs, labels)
    return analyze_jaxpr(closed, donated=donated, name=name, chip=chip,
                         hbm_budget_bytes=hbm_budget_bytes,
                         min_donation_bytes=min_donation_bytes)


# ---------------------------------------------------------------------------
# sharding readiness (S201–S204)
# ---------------------------------------------------------------------------

def _spec_entries(spec) -> List[Any]:
    """Normalize a PartitionSpec-like object to a list of per-dimension
    entries (each None, an axis name, or a tuple of axis names)."""
    if spec is None:
        return []
    return list(spec)


def _entry_axes(entry) -> List[str]:
    if entry is None:
        return []
    if isinstance(entry, (tuple, list)):
        return [str(a) for a in entry]
    return [str(entry)]


def check_sharding_readiness(layout: Dict[str, Any],
                             param_shapes: Dict[str, Sequence[int]],
                             mesh: Dict[str, int]) -> List[Diagnostic]:
    """Validate a ``{param_role: PartitionSpec}`` layout against an
    abstract mesh ``{axis_name: size}`` and the parameter shapes.

    - **S201** unknown mesh axis — the spec names an axis the mesh
      doesn't have.
    - **S202** duplicate axis within one spec — one axis cannot shard
      two dimensions of the same tensor.
    - **S203** rank mismatch — more partitioned entries than the param
      has dimensions.
    - **S204** non-divisible dimension — a dimension not divisible by
      the product of the mesh axes sharding it (GSPMD would pad or
      reject; either way the layout is not deployment-ready).

    All findings are ERROR severity: a layout that trips any of these
    cannot be handed to ``jax.jit(..., in_shardings=...)``.
    """
    mesh_sizes = dict(getattr(mesh, "shape", None) or mesh)
    diags: List[Diagnostic] = []
    for role in sorted(layout):
        spec = layout[role]
        where = f"layout[{role!r}]"
        entries = _spec_entries(spec)
        seen: Dict[str, int] = {}
        for dim, entry in enumerate(entries):
            for axis in _entry_axes(entry):
                if axis not in mesh_sizes:
                    diags.append(Diagnostic(
                        "S201", ERROR,
                        f"spec names mesh axis {axis!r} but the mesh has "
                        f"axes {sorted(mesh_sizes)} — unknown axis can "
                        "never be materialized", where))
                if axis in seen:
                    diags.append(Diagnostic(
                        "S202", ERROR,
                        f"axis {axis!r} appears in dims {seen[axis]} and "
                        f"{dim} of the same spec — one mesh axis cannot "
                        "shard two dimensions of one tensor", where))
                else:
                    seen[axis] = dim
        shape = param_shapes.get(role)
        if shape is None:
            continue
        shape = tuple(int(s) for s in shape)
        if len(entries) > len(shape):
            diags.append(Diagnostic(
                "S203", ERROR,
                f"spec has {len(entries)} entries but param {role!r} has "
                f"rank {len(shape)} ({shape}) — rank mismatch", where))
            continue
        for dim, entry in enumerate(entries):
            axes = [a for a in _entry_axes(entry) if a in mesh_sizes]
            if not axes:
                continue
            factor = int(np.prod([mesh_sizes[a] for a in axes],
                                 dtype=np.int64))
            if factor and shape[dim] % factor != 0:
                product = " × ".join(f"{a}={mesh_sizes[a]}" for a in axes)
                diags.append(Diagnostic(
                    "S204", ERROR,
                    f"dim {dim} of {role!r} has size {shape[dim]}, not "
                    f"divisible by the mesh-axis product {product} = "
                    f"{factor} — GSPMD would pad every shard; pick a "
                    "divisible dim or resize the mesh", where))
    from .hazards import sort_diagnostics

    return sort_diagnostics(diags)


# ---------------------------------------------------------------------------
# observability mirror + registered-step audit
# ---------------------------------------------------------------------------

def export_report_gauges(report: ProgramReport):
    """Mirror a report's headline statics into the observability
    registry (no-op when telemetry is disabled)."""
    from .. import observability

    if not observability.enabled():
        return
    reg = observability.get_registry()
    reg.gauge("xray_static_flops",
              "statically-modeled FLOPs of a traced step").set(
        report.flops, step=report.name)
    reg.gauge("xray_static_bytes",
              "statically-modeled HBM bytes moved by a traced step").set(
        report.bytes, step=report.name)
    reg.gauge("xray_peak_hbm_bytes",
              "liveness-walk peak live HBM of a traced step").set(
        report.peak_hbm_bytes, step=report.name)
    g = reg.gauge("xray_peak_hbm_bytes_by_dtype",
                  "bytes of one dtype held at the liveness-walk peak")
    for dt, b in sorted(report.peak_hbm_by_dtype.items()):
        g.set(b, step=report.name, dtype=dt)


def _serving_abstract_args(model, *, batch, num_blocks, block_size,
                           max_blocks_per_seq, chunk_tokens,
                           kv_cache_dtype=None):
    """Engine-shaped abstract args for the paged decode and chunked
    prefill steps (mirrors Engine.__init__'s concrete buffers).
    ``kv_cache_dtype`` of "int8"/"fp8" mirrors a QUANTIZED pool: int8
    code pools plus per-(block, token)-row f32 scale sidecars, so the
    liveness walk prices the real (quantized) HBM bytes per dtype."""
    from ..kernels.kv_quant import resolve_kv_cache_dtype
    from ..models.generation import _cache_dims

    kv_heads, head_dim, dtype = _cache_dims(model)
    scheme = resolve_kv_cache_dtype(kv_cache_dtype)
    sds = jax.ShapeDtypeStruct
    if scheme is not None:
        pool_sds = sds((num_blocks, block_size, kv_heads, head_dim),
                       np.int8)
        scale_sds = sds((num_blocks, block_size), np.float32)
        pool = [(pool_sds, pool_sds, scale_sds, scale_sds)
                for _ in range(model.config.num_hidden_layers)]
    else:
        pool = [(sds((num_blocks, block_size, kv_heads, head_dim), dtype),
                 sds((num_blocks, block_size, kv_heads, head_dim), dtype))
                for _ in range(model.config.num_hidden_layers)]
    decode = (sds((batch, 1), np.int32), pool,
              sds((batch, max_blocks_per_seq), np.int32),
              sds((batch,), np.int32))
    prefill = (sds((1, chunk_tokens), np.int32), pool,
               sds((1, max_blocks_per_seq), np.int32),
               sds((1,), np.int32),
               sds((), np.int32))
    return decode, prefill


def audit_default_steps(*, chip: str = "cpu",
                        hbm_budget_bytes: Optional[int] = None,
                        fused: bool = False
                        ) -> List[ProgramReport]:
    """Build tiny Llama models and X-ray all five default step kinds
    (train, paged decode, chunked prefill, MoE block, ring/sp block) —
    the ``lint_tpu.py --xray`` / CI entry point.  Returns the reports;
    callers gate on ``report.errors()``.

    ``fused=True`` additionally audits the FUSED serving steps
    (``serving::decode_step[fused]`` / ``serving::prefill_step[fused]``,
    forced via models.generation's ``fused=True`` so the programs carry
    the fused kernels even off-TPU) — the ``lint_tpu.py --xray --fused``
    / CI gate that the pallas_call leaves price cleanly."""
    import paddle_tpu as paddle
    from .. import nn
    from ..models import LlamaConfig, LlamaForCausalLM
    from ..optimizer import AdamW

    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    net = LlamaForCausalLM(cfg)
    reports: List[ProgramReport] = []

    model = paddle.Model(net)
    model.prepare(AdamW(1e-3, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    ids = np.zeros((2, 16), np.int64)
    reports.append(analyze_train_step(
        model._train_step_fn, [paddle.to_tensor(ids[:, :-1])],
        [paddle.to_tensor(ids[:, 1:])], chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))

    from ..models.generation import (make_chunked_prefill_step,
                                     make_paged_decode_step)

    net.eval()
    decode_args, prefill_args = _serving_abstract_args(
        net, batch=4, num_blocks=32, block_size=8,
        max_blocks_per_seq=8, chunk_tokens=32)
    reports.append(analyze(
        make_paged_decode_step(net), decode_args,
        name="serving::decode_step", chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))
    reports.append(analyze(
        make_chunked_prefill_step(net), prefill_args,
        name="serving::prefill_step", chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))

    # sampled + speculative serving steps (ISSUE 19): same pool/table
    # geometry as the plain decode step, plus the per-slot sampling
    # state (temps/top_ks/top_ps/keys/counters) and, for verify, the
    # K-token draft proposals with their filtered distributions
    from ..serving.sampling import make_sampled_decode_step
    from ..serving.speculative import make_spec_verify_step

    sds = jax.ShapeDtypeStruct
    batch, num_draft = 4, 4
    sampling_state = (sds((batch,), np.float32),          # temps
                     sds((batch,), np.int32),             # top_ks
                     sds((batch,), np.float32),           # top_ps
                     sds((batch, 2), np.uint32),          # keys
                     sds((batch,), np.int32))             # counters
    reports.append(analyze(
        make_sampled_decode_step(net), decode_args + sampling_state,
        name="serving::sampled_decode_step", chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))
    pool_arg, table_arg, lengths_arg = decode_args[1:4]
    verify_args = (sds((batch,), np.int32),               # pending
                   sds((batch, num_draft), np.int32),     # proposals
                   sds((batch, num_draft, cfg.vocab_size),
                       np.float32),                       # draft_probs
                   pool_arg, table_arg, lengths_arg) + sampling_state
    reports.append(analyze(
        make_spec_verify_step(net, num_draft), verify_args,
        name="serving::spec_verify_step", chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))
    if fused:
        reports.append(analyze(
            make_paged_decode_step(net, fused=True), decode_args,
            name="serving::decode_step[fused]", chip=chip,
            hbm_budget_bytes=hbm_budget_bytes))
        reports.append(analyze(
            make_chunked_prefill_step(net, fused=True), prefill_args,
            name="serving::prefill_step[fused]", chip=chip,
            hbm_budget_bytes=hbm_budget_bytes))
        # off-TPU the fused steps lower to the XLA fallback, so ALSO
        # audit the decode kernel itself in interpret mode — this is
        # the gate that a real pallas_call leaf prices through the
        # kernels.costs registry on any backend
        from ..kernels.paged_attention import fused_paged_decode

        hd = cfg.hidden_size // cfg.num_attention_heads
        kvh = cfg.num_key_value_heads
        f32 = np.float32
        sds32 = jax.ShapeDtypeStruct
        kernel_args = (
            sds32((4, 1, cfg.num_attention_heads, hd), f32),    # q
            sds32((4, 1, kvh, hd), f32),                        # k_new
            sds32((4, 1, kvh, hd), f32),                        # v_new
            sds32((32, 8, kvh, hd), f32),                       # k_pool
            sds32((32, 8, kvh, hd), f32),                       # v_pool
            sds32((4, 8), np.int32),                            # table
            sds32((4,), np.int32),                              # pos
            sds32((cfg.max_position_embeddings, hd // 2), f32),  # cos
            sds32((cfg.max_position_embeddings, hd // 2), f32),  # sin
        )
        reports.append(analyze(
            functools.partial(fused_paged_decode, use_pallas=True,
                              interpret=True),
            kernel_args, name="kernel::fused_paged_decode", chip=chip,
            hbm_budget_bytes=hbm_budget_bytes))

        from ..kernels.chunked_prefill import fused_chunked_attention

        prefill_kernel_args = (
            sds32((4, 32, cfg.num_attention_heads, hd), f32),   # q chunk
            sds32((32, 8, kvh, hd), f32),                       # k_pool
            sds32((32, 8, kvh, hd), f32),                       # v_pool
            sds32((4, 8), np.int32),                            # table
            sds32((4,), np.int32),                              # pos
        )
        reports.append(analyze(
            functools.partial(fused_chunked_attention, use_pallas=True,
                              interpret=True),
            prefill_kernel_args, name="kernel::fused_chunked_prefill",
            chip=chip, hbm_budget_bytes=hbm_budget_bytes))

        # quantized serving (ISSUE 20): the int8-KV fused steps and the
        # quantized decode kernel, so the costs registry is exercised on
        # int8 pool operands (quantized bytes, not fp32) in the same
        # --xray --fused CI gate
        q_decode_args, q_prefill_args = _serving_abstract_args(
            net, batch=4, num_blocks=32, block_size=8,
            max_blocks_per_seq=8, chunk_tokens=32, kv_cache_dtype="int8")
        reports.append(analyze(
            make_paged_decode_step(net, fused=True, kv_cache_dtype="int8"),
            q_decode_args, name="serving::decode_step[fused,int8]",
            chip=chip, hbm_budget_bytes=hbm_budget_bytes))
        reports.append(analyze(
            make_chunked_prefill_step(net, fused=True,
                                      kv_cache_dtype="int8"),
            q_prefill_args, name="serving::prefill_step[fused,int8]",
            chip=chip, hbm_budget_bytes=hbm_budget_bytes))

        def _q_decode_kernel(q, kn, vn, kp, vp, bt, pos, cos, sin,
                             ksc, vsc):
            return fused_paged_decode(
                q, kn, vn, kp, vp, bt, pos, cos, sin, use_pallas=True,
                interpret=True, k_scale=ksc, v_scale=vsc,
                kv_cache_dtype="int8")

        q_kernel_args = kernel_args[:3] + (
            sds32((32, 8, kvh, hd), np.int8),               # k_pool codes
            sds32((32, 8, kvh, hd), np.int8),               # v_pool codes
        ) + kernel_args[5:] + (
            sds32((32, 8), f32),                            # k_scale
            sds32((32, 8), f32),                            # v_scale
        )
        reports.append(analyze(
            _q_decode_kernel, q_kernel_args,
            name="kernel::fused_paged_decode[int8]", chip=chip,
            hbm_budget_bytes=hbm_budget_bytes))

    from ..distributed.mesh import abstract_mesh
    from ..models.generation import make_moe_block_step, make_ring_sp_step

    sds = jax.ShapeDtypeStruct
    moe_net = LlamaForCausalLM(LlamaConfig.tiny(
        moe_num_experts=4, moe_top_k=2, moe_capacity_factor=2.0))
    moe_net.eval()
    reports.append(analyze(
        make_moe_block_step(moe_net), (sds((4, 16), np.int32),),
        name="moe::block_step", chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))

    ring_net = LlamaForCausalLM(LlamaConfig.tiny(context_parallel="ring"))
    ring_net.eval()
    ring_mesh = abstract_mesh({"data": 2, "sp": 2, "tp": 2})
    reports.append(analyze(
        make_ring_sp_step(ring_net, mesh=ring_mesh),
        (sds((4, 32), np.int32),),
        name="ring::sp_step", chip=chip,
        hbm_budget_bytes=hbm_budget_bytes))
    for r in reports:
        export_report_gauges(r)
    return reports
