"""TPU-hazard detector over recorded Programs and ``@to_static`` code.

"Operator Fusion in XLA: Analysis and Evaluation" (PAPERS.md) shows that
end-to-end TPU throughput is dominated not by kernel quality but by the
defects AROUND the compiled region: recompilation storms, host round
trips, and precision-widening ops XLA must honor.  This module flags
exactly that class of defect:

- **H101 scalar-capture retrace**: a ``@to_static`` function whose
  compile cache holds multiple entries that differ ONLY in captured
  Python scalar/shape values — every new value triggers a full XLA
  recompile (minutes on a real TPU), the classic "loss curve pauses
  every step" bug.  Detected from the live ``StaticFunction`` cache, so
  it sees what actually happened rather than guessing from source.
- **H102 host sync in traced region**: ``.numpy()`` / ``.item()`` /
  ``.tolist()`` / ``to_np(...)`` / ``float(tensor)`` inside a function
  that compiles — each forces a device→host transfer and serializes the
  pipeline (and under trace, usually a ConcretizationTypeError at best).
- **H103 float64 upcast**: literal ``float64``/``double`` dtypes in
  traced code or recorded programs.  TPUs emulate f64 in software; one
  stray ``np.float64`` mean poisons a whole fused region.
- **H104 weak-type promotion leak**: a recorded op whose output is
  WIDER than every one of its tensor inputs — a Python scalar or weak-
  typed constant silently promoted the computation.
- **H105 zero-trip loop-var deviation**: a ``range()`` for-loop with
  ``break``/``continue``/``return`` in its body compiles through
  ``jit.dy2static._range_for_to_while``, whose documented deviation is
  that an EMPTY range leaves the loop variable at ``start`` instead of
  its prior binding (MIGRATING.md "dy2static constraints").
- **H106 host work in a decode step**: the serving hot loop runs one
  compiled decode step PER TOKEN; a ``.item()``/``.numpy()``-style host
  sync inside a registered step (models/generation.py
  ``register_decode_step``) stalls the device once per generated token
  (ERROR), and Python ``if``/``while`` branching on traced values bakes
  one executable per branch outcome — a retrace per token at worst
  (WARNING).  ``scan_decode_steps()`` audits every live registered step.
- **H111 wall-clock deadline**: ``time.time()`` used where a DURATION
  matters — deadlines, timeouts, watchdog budgets — in serving or
  resilience code.  The wall clock steps under NTP slews and leap
  smears, so a deadline armed from it can fire early, late, or never;
  ``time.monotonic()`` is the contract
  (``scheduler.Request.deadline_t``, the serving step watchdog).
  ``scan_wall_clock_deadlines()`` audits source trees: ``time.time()``
  near deadline/timeout vocabulary is an ERROR, elsewhere a WARNING
  (timestamps for logs/filenames are legitimate wall-clock uses, but
  deserve a look when they sit in serving/resilience paths).

- **H113 multi-process checkpoint write race**: a filesystem write
  (``open(..., 'w')``, ``np.save``, ``os.rename``/``os.replace``
  commit) on a checkpoint-hinted path (``ckpt``/``checkpoint``/
  ``manifest``/``staging``/``shard``) that is neither gated on the
  coordinator (``process_index() == 0`` / ``is_coordinator`` /
  rank test) nor made per-process-unique (``getpid``/``uuid``/
  ``process``/``rank`` in the name).  Under ``jax.distributed`` every
  host runs the same Python, so an ungated write means N processes
  racing one path over shared storage — the classic torn-manifest
  corruption the sharded checkpoint protocol exists to prevent.
  ``scan_process_write_races()`` audits source trees; the sanctioned
  atomic-writer modules (which implement the gating) are excluded,
  and a deliberate single-process write is suppressed with
  ``# lint-tpu: disable=H113`` on the flagged line.

- **H112 single-process device-count assumption**:
  ``jax.device_count()`` / ``len(jax.devices())`` return the GLOBAL
  device count — under ``jax.distributed`` a process can only address
  its ``jax.local_device_count()`` chips, so sizing a per-process mesh,
  loop, or placement list from the global count breaks the moment a
  second host joins (WARNING); a hardcoded chip count passed to a mesh
  constructor (``Mesh``/``init_mesh``/``make_mesh``/
  ``create_device_mesh``) bakes one fleet shape into code that should
  derive it from the runtime (ERROR).
  ``scan_device_count_assumptions()`` audits source trees; suppress a
  deliberate global-count use with ``# lint-tpu: disable=H112`` on the
  flagged line.

Program-level scans are pure metadata walks (no execution); source-level
scans are AST walks with real file/line locations.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Any, List, Optional

from .verifier import ERROR, INFO, WARNING, Diagnostic

__all__ = [
    "scan_program",
    "scan_function",
    "scan_static_function",
    "scan_decode_step",
    "scan_decode_steps",
    "scan_checkpoint_writes",
    "scan_wall_clock_deadlines",
    "scan_device_count_assumptions",
    "scan_process_write_races",
    "scan",
    "sort_diagnostics",
]

_HOST_SYNC_ATTRS = ("numpy", "item", "tolist", "cpu")
_HOST_SYNC_CALLS = ("to_np",)
_F64_NAMES = ("float64", "double")

_WHERE_RE = None  # compiled lazily (re import below is cheap but explicit)


def _where_key(where: str):
    """(file, line) sort key from a ``file:line`` location string;
    non-positional locations ('cache of f', 'block 0 op 3') sort by the
    raw string with line 0."""
    global _WHERE_RE
    if _WHERE_RE is None:
        import re

        _WHERE_RE = re.compile(r"^(?P<file>.*):(?P<line>\d+)$")
    m = _WHERE_RE.match(where or "")
    if m:
        return (m.group("file"), int(m.group("line")))
    return (where or "", 0)


def sort_diagnostics(diags: List[Diagnostic]) -> List[Diagnostic]:
    """Deterministic order: (file, line, code).  Every multi-source scan
    entry point returns through here so CI diffs and test assertions
    never flake on dict/registry ordering (sort is stable, so
    same-location diagnostics keep their discovery order)."""
    return sorted(diags, key=lambda d: _where_key(d.where) + (d.code,))


# ---------------------------------------------------------------------------
# recorded-Program scans
# ---------------------------------------------------------------------------

def _op_tensor_in_widths(op):
    widths = []
    for kind, ref in op.inputs:
        v = getattr(ref, "_value", None)
        if kind in ("var", "const") and v is not None:
            try:
                widths.append(v.dtype.itemsize)
            except (AttributeError, TypeError):
                pass
    return widths


def scan_program(program) -> List[Diagnostic]:
    """Flag TPU hazards recorded into a static Program."""
    diags: List[Diagnostic] = []
    for block in program.blocks:
        for op_idx, op in enumerate(block.ops):
            where = f"block {block.idx} op {op_idx} ({op.type})"
            in_widths = _op_tensor_in_widths(op)
            for o in op.outputs:
                dt = getattr(getattr(o, "_value", None), "dtype", None)
                if dt is None:
                    continue
                name = getattr(dt, "name", str(dt))
                if name in ("float64", "complex128"):
                    diags.append(Diagnostic(
                        "H103", ERROR,
                        f"output '{o.name}' is {name}: TPUs have no "
                        "native f64 — this op (and everything fused "
                        "with it) runs software-emulated", where))
                elif in_widths and hasattr(dt, "itemsize") and \
                        dt.itemsize > max(in_widths) and \
                        name.startswith(("float", "int", "uint")):
                    diags.append(Diagnostic(
                        "H104", WARNING,
                        f"output '{o.name}' ({name}) is wider than every "
                        "tensor input — a Python scalar or weak-typed "
                        "constant promoted this op", where))
    return diags


# ---------------------------------------------------------------------------
# source-level scans
# ---------------------------------------------------------------------------

class _SourceScanner(ast.NodeVisitor):
    def __init__(self, filename: str, firstline: int):
        self.filename = filename
        self.firstline = firstline
        self.diags: List[Diagnostic] = []
        self._loop_depth = 0

    def _where(self, node) -> str:
        return f"{self.filename}:{self.firstline + node.lineno - 1}"

    def add(self, code, severity, message, node):
        self.diags.append(
            Diagnostic(code, severity, message, self._where(node)))

    # -- host syncs ------------------------------------------------------
    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _HOST_SYNC_ATTRS \
                and not node.args and not node.keywords:
            self.add(
                "H102", ERROR,
                f".{fn.attr}() inside a traced region forces a device→"
                "host sync (and fails outright under jit tracing); "
                "fetch values OUTSIDE the compiled function", node)
        elif isinstance(fn, ast.Name) and fn.id in _HOST_SYNC_CALLS:
            self.add(
                "H102", ERROR,
                f"{fn.id}(...) inside a traced region materializes the "
                "value on host — a device→host sync per call", node)
        elif isinstance(fn, ast.Attribute) and fn.attr in (
                "asarray", "array") and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("np", "numpy"):
            self.add(
                "H102", WARNING,
                f"{fn.value.id}.{fn.attr}(...) on a traced value is a "
                "host sync; use paddle/jnp ops instead", node)
        # dtype strings only count as hazards when passed to a call
        # (astype('float64'), cast(x, 'float64'), dtype='float64') —
        # a bare string constant may be a docstring or message
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and arg.value in _F64_NAMES:
                self.add(
                    "H103", WARNING,
                    f"dtype '{arg.value}' passed to a call: TPUs emulate "
                    "f64 in software — use float32/bfloat16 unless the "
                    "extra mantissa is load-bearing", arg)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr in _F64_NAMES and isinstance(node.value, ast.Name) \
                and node.value.id in ("np", "numpy", "jnp", "paddle"):
            self.add(
                "H103", WARNING,
                f"{node.value.id}.{node.attr} upcasts to f64 — software-"
                "emulated on TPU", node)
        self.generic_visit(node)

    # -- zero-trip range-for deviation ----------------------------------
    def visit_For(self, node):
        it = node.iter
        is_range = (isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id == "range")
        if is_range and _body_has_break_continue_return(node.body):
            tgt = node.target.id if isinstance(node.target, ast.Name) \
                else "<loop var>"
            self.add(
                "H105", INFO,
                f"range-for with break/continue/return lowers through "
                "dy2static's explicit-while form: on a ZERO-iteration "
                f"range the loop variable '{tgt}' is left at the range "
                "start instead of keeping its prior binding (see "
                "MIGRATING.md, dy2static constraints)", node)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_While(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1


def _body_has_break_continue_return(stmts) -> bool:
    found = [False]

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            return

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_For(self, node):  # nested loops own their break/continue
            for s in ast.walk(node):
                if isinstance(s, ast.Return):
                    found[0] = True
            return

        visit_While = visit_For

        def visit_Break(self, node):
            found[0] = True

        def visit_Continue(self, node):
            found[0] = True

        def visit_Return(self, node):
            found[0] = True

    for s in stmts:
        V().visit(s)
    return found[0]


def scan_function(fn) -> List[Diagnostic]:
    """AST-scan a function that will be traced (``@to_static`` target,
    jit.save export, or a dy2static conversion candidate)."""
    raw = inspect.unwrap(getattr(fn, "_fn", fn))
    raw = getattr(raw, "__func__", raw)
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        filename = inspect.getsourcefile(raw) or "<unknown>"
        firstline = inspect.getsourcelines(raw)[1]
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return []
    scanner = _SourceScanner(filename, firstline)
    scanner.visit(tree)
    return scanner.diags


# ---------------------------------------------------------------------------
# decode-step scans (serving hot loop)
# ---------------------------------------------------------------------------

class _DecodeStepScanner(ast.NodeVisitor):
    """H106: the body of a decode step runs once PER GENERATED TOKEN, so
    hazards that are merely slow elsewhere are per-token stalls here."""

    def __init__(self, filename: str, firstline: int, name: str):
        self.filename = filename
        self.firstline = firstline
        self.name = name
        self.diags: List[Diagnostic] = []

    def _where(self, node) -> str:
        return f"{self.filename}:{self.firstline + node.lineno - 1}"

    def add(self, severity, message, node):
        self.diags.append(
            Diagnostic("H106", severity, message, self._where(node)))

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _HOST_SYNC_ATTRS \
                and not node.args and not node.keywords:
            self.add(
                ERROR,
                f"decode step '{self.name}' calls .{fn.attr}() — a device→"
                "host sync once per generated token; keep the hot loop "
                "device-side and fetch results after retirement", node)
        elif isinstance(fn, ast.Name) and fn.id in _HOST_SYNC_CALLS:
            self.add(
                ERROR,
                f"decode step '{self.name}' calls {fn.id}(...) — "
                "materializes on host once per generated token", node)
        self.generic_visit(node)

    def _branch(self, node, kind):
        self.add(
            WARNING,
            f"decode step '{self.name}' has a Python {kind} — branching "
            "on a traced value fails outright, and branching on a "
            "captured scalar bakes one executable per outcome (a retrace "
            "per token at worst); use lax.select/where so ONE program "
            "serves every iteration", node)

    def visit_If(self, node):
        self._branch(node, "'if'")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._branch(node, "conditional expression")
        self.generic_visit(node)

    def visit_While(self, node):
        self._branch(node, "'while' loop")
        self.generic_visit(node)


def scan_decode_step(fn) -> List[Diagnostic]:
    """AST-audit one decode-step function (the raw Python function behind
    a compiled serving step) for H106 hazards: host syncs (ERROR) and
    Python branching (WARNING) inside the per-token hot loop."""
    raw = inspect.unwrap(getattr(fn, "_fn", fn))
    raw = getattr(raw, "__func__", raw)
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        filename = inspect.getsourcefile(raw) or "<unknown>"
        firstline = inspect.getsourcelines(raw)[1]
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return []
    scanner = _DecodeStepScanner(
        filename, firstline, getattr(raw, "__name__", repr(fn)))
    scanner.visit(tree)
    return scanner.diags


def scan_decode_steps() -> List[Diagnostic]:
    """Audit every LIVE decode step registered via
    ``models.generation.register_decode_step`` (the built-in greedy/
    beam/prefill/paged steps plus any user-registered custom step)."""
    from ..models.generation import registered_decode_steps

    diags: List[Diagnostic] = []
    for fn in registered_decode_steps():
        diags.extend(scan_decode_step(fn))
    return sort_diagnostics(diags)


# ---------------------------------------------------------------------------
# checkpoint-write scans (resilience)
# ---------------------------------------------------------------------------

_CKPT_PATH_HINTS = ("ckpt", "checkpoint")
# modules allowed to write checkpoint bytes directly: the atomic
# writers themselves
_CKPT_SANCTIONED = ("resilience/checkpoint.py", "distributed/checkpoint.py",
                    "framework/io.py")


def _mentions_checkpoint(node) -> bool:
    """Any identifier/attribute/string inside the expression smells like
    a checkpoint path (``ckpt``/``checkpoint`` substring)."""
    for n in ast.walk(node):
        text = None
        if isinstance(n, ast.Name):
            text = n.id
        elif isinstance(n, ast.Attribute):
            text = n.attr
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            text = n.value
        if text is not None and any(h in text.lower()
                                    for h in _CKPT_PATH_HINTS):
            return True
    return False


class _CheckpointWriteScanner(ast.NodeVisitor):
    """H107: checkpoint bytes written OUTSIDE the atomic writer.  A
    direct ``np.save``/``open(..., 'wb')`` on a checkpoint path commits
    non-atomically and unverified — a crash mid-write destroys the only
    copy (the exact defect ``resilience.ResilientCheckpointer`` and the
    ``distributed.checkpoint`` temp+rename fallback exist to prevent)."""

    def __init__(self, filename: str, firstline: int = 1):
        self.filename = filename
        self.firstline = firstline
        self.diags: List[Diagnostic] = []

    def _where(self, node) -> str:
        return f"{self.filename}:{self.firstline + node.lineno - 1}"

    def add(self, severity, message, node):
        self.diags.append(
            Diagnostic("H107", severity, message, self._where(node)))

    def visit_Call(self, node):
        fn = node.func
        # np.save / np.savez / np.savez_compressed(ckpt_path, ...)
        if isinstance(fn, ast.Attribute) \
                and fn.attr in ("save", "savez", "savez_compressed") \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("np", "numpy") \
                and node.args and _mentions_checkpoint(node.args[0]):
            self.add(
                ERROR,
                f"{fn.value.id}.{fn.attr}(...) writes a checkpoint path "
                "directly — non-atomic, no integrity manifest; a crash "
                "mid-write destroys the only copy.  Route through "
                "resilience.ResilientCheckpointer (or temp file + "
                "os.replace at minimum)", node)
        # open(ckpt_path, "wb"/"w")
        elif isinstance(fn, ast.Name) and fn.id == "open" and node.args:
            mode = ""
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if "w" in mode and _mentions_checkpoint(node.args[0]):
                self.add(
                    ERROR,
                    f"open(..., {mode!r}) on a checkpoint path bypasses "
                    "the atomic writer — the write is torn by any crash "
                    "and never checksummed.  Route through "
                    "resilience.ResilientCheckpointer (or temp file + "
                    "os.replace at minimum)", node)
        # <anything>.save(obj, ckpt_path) / save(obj, ckpt_path) —
        # pickle-style direct save onto a checkpoint path
        elif ((isinstance(fn, ast.Attribute) and fn.attr == "save")
              or (isinstance(fn, ast.Name) and fn.id in ("save", "fsave"))) \
                and len(node.args) >= 2 \
                and _mentions_checkpoint(node.args[1]):
            name = fn.attr if isinstance(fn, ast.Attribute) else fn.id
            self.add(
                WARNING,
                f"{name}(..., <checkpoint path>) commits without temp-"
                "file+rename or a checksum manifest; prefer "
                "resilience.ResilientCheckpointer so a torn save cannot "
                "shadow the last good checkpoint", node)
        self.generic_visit(node)


def scan_checkpoint_writes(paths, exclude=_CKPT_SANCTIONED
                           ) -> List[Diagnostic]:
    """H107-audit python sources for checkpoint writes that bypass the
    atomic writer.  ``paths`` is a file, a directory (walked for
    ``.py``), or a list of either; ``exclude`` suffixes name the
    sanctioned writer modules themselves."""
    import os

    if isinstance(paths, (str, bytes)):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        else:
            files.append(p)
    diags: List[Diagnostic] = []
    for f in sorted(files):
        norm = f.replace("\\", "/")
        if any(norm.endswith(sfx) for sfx in exclude):
            continue
        try:
            with open(f, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError):
            continue
        scanner = _CheckpointWriteScanner(f)
        scanner.visit(tree)
        diags.extend(scanner.diags)
    return sort_diagnostics(diags)


# ---------------------------------------------------------------------------
# wall-clock deadline scan (serving / resilience)
# ---------------------------------------------------------------------------

# vocabulary that marks a time value as a DURATION/DEADLINE use, where
# only the monotonic clock is correct (NTP steps move the wall clock)
_H111_HINTS = ("deadline", "timeout", "watchdog", "expir", "budget",
               "slo", "stall", "elapsed", "retry")


def _h111_texts(node) -> List[str]:
    """Identifier-ish strings inside ``node`` (names, attributes,
    argument names) to match the deadline vocabulary against."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
        elif isinstance(n, ast.arg):
            out.append(n.arg)
    return out


class _WallClockScanner(ast.NodeVisitor):
    """H111: ``time.time()`` in deadline/timeout/watchdog logic.  The
    wall clock is for TIMESTAMPS (log lines, filenames); arming a
    deadline or measuring a budget from it breaks under NTP slews and
    clock steps — ``time.monotonic()`` is the serving/resilience
    contract (``Request.deadline_t``, the step watchdog)."""

    def __init__(self, filename: str):
        self.filename = filename
        self.diags: List[Diagnostic] = []
        self._fn_stack: List[str] = []
        self._stmt_stack: List[ast.stmt] = []

    def visit(self, node):
        is_stmt = isinstance(node, ast.stmt)
        if is_stmt:
            self._stmt_stack.append(node)
        super().visit(node)
        if is_stmt:
            self._stmt_stack.pop()

    def visit_FunctionDef(self, node):
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "time" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            texts = list(self._fn_stack)
            if self._stmt_stack:
                texts += _h111_texts(self._stmt_stack[-1])
            hinted = any(h in t.lower() for t in texts
                         for h in _H111_HINTS)
            where = f"{self.filename}:{node.lineno}"
            if hinted:
                self.diags.append(Diagnostic(
                    "H111", ERROR,
                    "time.time() arms a deadline/timeout/watchdog — the "
                    "wall clock steps under NTP slews, so the deadline "
                    "can fire early, late, or never; use "
                    "time.monotonic() (the Request.deadline_t contract)",
                    where))
            else:
                self.diags.append(Diagnostic(
                    "H111", WARNING,
                    "time.time() in serving/resilience code: fine for a "
                    "timestamp, wrong for any duration or deadline — "
                    "confirm, or switch to time.monotonic()", where))
        self.generic_visit(node)


def scan_wall_clock_deadlines(paths) -> List[Diagnostic]:
    """H111-audit python sources for ``time.time()`` used where only
    the monotonic clock is correct.  ``paths`` is a file, a directory
    (walked for ``.py``), or a list of either — typically
    ``paddle_tpu/serving`` and ``paddle_tpu/resilience``, whose
    deadline and watchdog semantics REQUIRE ``time.monotonic()``.
    Calls near deadline/timeout vocabulary are ERRORs, the rest
    WARNINGs."""
    import os

    if isinstance(paths, (str, bytes)):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        else:
            files.append(p)
    diags: List[Diagnostic] = []
    for f in sorted(files):
        try:
            with open(f, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError):
            continue
        scanner = _WallClockScanner(f)
        scanner.visit(tree)
        diags.extend(scanner.diags)
    return sort_diagnostics(diags)


#: callees whose arguments lay out devices — a hardcoded chip count
#: here bakes one fleet shape into the code (H112 ERROR).  abstract_mesh
#: is deliberately absent: it builds device-free simulation meshes for
#: the planner, where literal sizes are the point.
_MESH_CTORS = frozenset({
    "Mesh", "init_mesh", "make_mesh", "create_device_mesh",
    "ProcessMesh",
})


class _DeviceCountScanner(ast.NodeVisitor):
    """H112: single-process device-count assumptions.

    ``jax.device_count()`` and ``len(jax.devices())`` count the GLOBAL
    fleet; under ``jax.distributed`` only ``jax.local_device_count()``
    chips are addressable per process, so meshes/loops/placements sized
    from the global count double-count the moment a second host joins
    (WARNING — a global mesh over all processes is sometimes intended;
    suppress with ``# lint-tpu: disable=H112``).  An int literal > 1
    handed to a mesh constructor is an ERROR: the fleet shape belongs
    to runtime discovery or config, never the source."""

    def __init__(self, filename: str, lines: List[str]):
        self.filename = filename
        self.lines = lines
        self.diags: List[Diagnostic] = []

    def _suppressed(self, lineno: int) -> bool:
        if 1 <= lineno <= len(self.lines):
            return "lint-tpu: disable=H112" in self.lines[lineno - 1]
        return False

    def _emit(self, severity: str, message: str, lineno: int):
        if self._suppressed(lineno):
            return
        self.diags.append(Diagnostic(
            "H112", severity, message, f"{self.filename}:{lineno}"))

    @staticmethod
    def _is_jax_attr(node, attr: str) -> bool:
        return (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax")

    @staticmethod
    def _literal_counts(node) -> List[int]:
        """int literals > 1 inside an arg: bare, or in tuple/list/dict
        literals (``Mesh(devs.reshape(2, 4), ...)`` style reshapes are
        caught at the reshape call via the ctor's positional args)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool) and node.value > 1:
            return [node.value]
        out: List[int] = []
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                out.extend(_DeviceCountScanner._literal_counts(e))
        elif isinstance(node, ast.Dict):
            for v in node.values:
                out.extend(_DeviceCountScanner._literal_counts(v))
        return out

    def visit_Call(self, node):
        fn = node.func
        # jax.device_count()  (NOT jax.local_device_count())
        if self._is_jax_attr(fn, "device_count"):
            self._emit(WARNING,
                       "jax.device_count() is the GLOBAL device count — "
                       "under jax.distributed a process addresses only "
                       "jax.local_device_count() chips; sizing a "
                       "per-process mesh, loop, or placement list from "
                       "the global count breaks on the second host "
                       "(suppress if a global/world size is intended)",
                       node.lineno)
        # len(jax.devices())
        if isinstance(fn, ast.Name) and fn.id == "len" \
                and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Call) \
                and self._is_jax_attr(node.args[0].func, "devices"):
            self._emit(WARNING,
                       "len(jax.devices()) counts the GLOBAL fleet — "
                       "only jax.local_devices() are addressable per "
                       "process under jax.distributed; use "
                       "jax.local_device_count() for per-process "
                       "sizing (suppress if a global/world size is "
                       "intended)", node.lineno)
        # hardcoded chip count in a mesh constructor
        callee = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else None
        if callee in _MESH_CTORS:
            counts: List[int] = []
            for arg in list(node.args) + [k.value for k in node.keywords]:
                counts.extend(self._literal_counts(arg))
            if counts:
                self._emit(ERROR,
                           f"hardcoded chip count(s) {sorted(counts)} in "
                           f"{callee}(...) — the fleet shape is baked "
                           "into the source and silently wrong on any "
                           "other host/chip configuration; derive it "
                           "from jax.local_device_count() / "
                           "jax.process_count() or take it from config",
                           node.lineno)
        self.generic_visit(node)


def scan_device_count_assumptions(paths) -> List[Diagnostic]:
    """H112-audit python sources for single-process device-count
    assumptions.  ``paths`` is a file, a directory (walked for
    ``.py``), or a list of either — typically ``paddle_tpu/`` and
    ``examples/``.  Global-count reads (``jax.device_count()`` /
    ``len(jax.devices())``) are WARNINGs, hardcoded chip counts in mesh
    construction are ERRORs; suppress a deliberate global-count use
    with ``# lint-tpu: disable=H112`` on the flagged line."""
    import os

    if isinstance(paths, (str, bytes)):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        else:
            files.append(p)
    diags: List[Diagnostic] = []
    for f in sorted(files):
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        if "lint-tpu: disable-file=H112" in src:
            continue
        scanner = _DeviceCountScanner(f, src.splitlines())
        scanner.visit(tree)
        diags.extend(scanner.diags)
    return sort_diagnostics(diags)


# ---------------------------------------------------------------------------
# multi-process checkpoint write-race scan (H113)
# ---------------------------------------------------------------------------

#: path vocabulary that marks a write target as checkpoint machinery —
#: the paths where an N-way clobber race corrupts recovery state
_H113_PATH_HINTS = ("ckpt", "checkpoint", "manifest", "staging", "shard")
#: identifier vocabulary that marks an ``if`` test as a process gate
_H113_GATE_HINTS = ("process_index", "process_id", "is_coordinator",
                    "process_count", "rank", "trainer_id", "coordinator")
#: path vocabulary that makes a write per-process-unique (no race even
#: when every host writes: each writes its OWN file)
_H113_UNIQUE_HINTS = ("getpid", "pid", "uuid", "process", "rank",
                      "trainer", "host_id", "local_", "worker")


def _h113_expr_mentions(node, vocab, taint=None, flag=None) -> bool:
    """Any identifier/attribute/string/f-string piece inside ``node``
    matches ``vocab``; a ``Name`` also matches when the per-function
    ``taint`` map carries ``flag`` for it (one-hop dataflow through
    simple assignments like ``path = os.path.join(d, 'manifest')``)."""
    for n in ast.walk(node):
        text = None
        if isinstance(n, ast.Name):
            text = n.id
            if taint is not None and flag in taint.get(n.id, ()):
                return True
        elif isinstance(n, ast.Attribute):
            text = n.attr
        elif isinstance(n, ast.arg):
            text = n.arg
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            text = n.value
        if text is not None and any(h in text.lower() for h in vocab):
            return True
    return False


class _ProcessWriteScanner(ast.NodeVisitor):
    """H113: checkpoint-path filesystem writes every process executes.

    A write is GATED (not flagged) when any lexically-enclosing ``if``
    tests process identity, or an earlier guard-return in the same
    function (``if process_index() != 0: return``) fences it.  A write
    is SAFE when its target path is per-process-unique.  Everything
    else on a checkpoint-hinted path is the race."""

    def __init__(self, filename: str, lines: List[str]):
        self.filename = filename
        self.lines = lines
        self.diags: List[Diagnostic] = []
        self._gate_depth = 0
        # lineno of each guard-return per enclosing function (stack)
        self._guard_lines: List[List[int]] = []
        self._taint: List[dict] = []

    # -- bookkeeping -----------------------------------------------------
    def _suppressed(self, lineno: int) -> bool:
        if 1 <= lineno <= len(self.lines):
            return "lint-tpu: disable=H113" in self.lines[lineno - 1]
        return False

    def visit_FunctionDef(self, node):
        self._guard_lines.append([])
        self._taint.append({})
        self.generic_visit(node)
        self._taint.pop()
        self._guard_lines.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        # one-hop taint: name = <expr mentioning hints/unique tokens>
        if self._taint and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            flags = set()
            if _h113_expr_mentions(node.value, _H113_PATH_HINTS,
                                   self._taint[-1], "hinted"):
                flags.add("hinted")
            if _h113_expr_mentions(node.value, _H113_UNIQUE_HINTS,
                                   self._taint[-1], "unique"):
                flags.add("unique")
            self._taint[-1][node.targets[0].id] = flags
        self.generic_visit(node)

    def visit_If(self, node):
        gated = _h113_expr_mentions(node.test, _H113_GATE_HINTS)
        if gated:
            # `if rank != 0: return` fences everything after it too
            if self._guard_lines and any(
                    isinstance(s, (ast.Return, ast.Raise, ast.Continue))
                    for s in node.body):
                self._guard_lines[-1].append(node.lineno)
            self._gate_depth += 1
        self.generic_visit(node)
        if gated:
            self._gate_depth -= 1

    def _is_gated(self, lineno: int) -> bool:
        if self._gate_depth > 0:
            return True
        return bool(self._guard_lines
                    and any(g < lineno for g in self._guard_lines[-1]))

    # -- write sites -----------------------------------------------------
    def _check_path(self, path_node, what, node):
        taint = self._taint[-1] if self._taint else {}
        if not _h113_expr_mentions(path_node, _H113_PATH_HINTS,
                                   taint, "hinted"):
            return
        if _h113_expr_mentions(path_node, _H113_UNIQUE_HINTS,
                               taint, "unique"):
            return
        if self._is_gated(node.lineno) or self._suppressed(node.lineno):
            return
        self.diags.append(Diagnostic(
            "H113", ERROR,
            f"{what} a checkpoint path with no process gate — under "
            "jax.distributed EVERY host runs this line, so N processes "
            "race one file over shared storage (torn manifest / clobbered "
            "shard).  Gate on bootstrap.is_coordinator() / "
            "process_index() == 0, or make the path per-process-unique",
            f"{self.filename}:{node.lineno}"))

    def visit_Call(self, node):
        fn = node.func
        # open(path, 'w'/'a'/...)
        if isinstance(fn, ast.Name) and fn.id == "open" and node.args:
            mode = ""
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if "w" in mode or "a" in mode or "x" in mode:
                self._check_path(node.args[0],
                                 f"open(..., {mode!r}) writes", node)
        # os.rename / os.replace — the COMMIT half of tmp+rename; racing
        # commits are exactly the torn-manifest failure
        elif isinstance(fn, ast.Attribute) \
                and fn.attr in ("rename", "replace", "renames") \
                and isinstance(fn.value, ast.Name) and fn.value.id == "os" \
                and len(node.args) >= 2:
            self._check_path(node.args[1], f"os.{fn.attr}(...) commits to",
                             node)
        # np.save / np.savez*(path, ...)
        elif isinstance(fn, ast.Attribute) \
                and fn.attr in ("save", "savez", "savez_compressed") \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("np", "numpy") and node.args:
            self._check_path(node.args[0], f"{fn.value.id}.{fn.attr}(...) "
                             "writes", node)
        # shutil.copy*/move(..., dst)
        elif isinstance(fn, ast.Attribute) \
                and fn.attr in ("copy", "copy2", "copyfile", "move") \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "shutil" and len(node.args) >= 2:
            self._check_path(node.args[1], f"shutil.{fn.attr}(...) "
                             "writes", node)
        self.generic_visit(node)


def scan_process_write_races(paths, exclude=_CKPT_SANCTIONED
                             ) -> List[Diagnostic]:
    """H113-audit python sources for checkpoint-path writes that every
    process would execute.  ``paths`` is a file, a directory (walked for
    ``.py``), or a list of either — typically ``paddle_tpu/`` and
    ``examples/``.  ``exclude`` suffixes name the sanctioned atomic-
    writer modules, which implement the per-process gating themselves;
    suppress a deliberate single-process write with
    ``# lint-tpu: disable=H113`` on the flagged line."""
    import os

    if isinstance(paths, (str, bytes)):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        else:
            files.append(p)
    diags: List[Diagnostic] = []
    for f in sorted(files):
        norm = f.replace("\\", "/")
        if any(norm.endswith(sfx) for sfx in exclude):
            continue
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        if "lint-tpu: disable-file=H113" in src:
            continue
        scanner = _ProcessWriteScanner(f, src.splitlines())
        scanner.visit(tree)
        diags.extend(scanner.diags)
    return sort_diagnostics(diags)


# ---------------------------------------------------------------------------
# live StaticFunction scans
# ---------------------------------------------------------------------------

def scan_static_function(sfn, retrace_threshold: int = 2
                         ) -> List[Diagnostic]:
    """Inspect a live ``StaticFunction``: source hazards (H102/H103/H105)
    plus the compile-cache retrace analysis (H101).

    The cache key is ``((dyn_specs, static_values, treedef), state_sig,
    mode_key, mesh_token)``; entries sharing everything but
    ``static_values`` mean the function recompiled once per captured
    Python scalar value.
    """
    diags = scan_function(sfn)
    cache = getattr(sfn, "_cache", None)
    if not cache:
        return diags
    groups = {}
    for key in cache:
        try:
            # mesh_token (the bound MeshExecutor's identity) joined the
            # key when runtime mesh execution landed; recompiling for a
            # DIFFERENT mesh is a new program by design, not a retrace
            (dyn, stat, treedef), state_sig, mode_key, mesh_token = key
        except (TypeError, ValueError):
            continue
        groups.setdefault((dyn, treedef, state_sig, mode_key, mesh_token),
                          []).append(stat)
    name = getattr(sfn, "__name__", repr(sfn))
    for (dyn, _td, _sig, _mode, _mesh), stats in groups.items():
        if len(stats) >= retrace_threshold:
            seen_vals = sorted({repr(s) for s in stats})
            diags.append(Diagnostic(
                "H101", ERROR,
                f"'{name}' recompiled {len(stats)}x for identical tensor "
                f"shapes {list(dyn)} but different captured Python "
                f"values ({', '.join(seen_vals[:4])}"
                f"{', ...' if len(seen_vals) > 4 else ''}) — pass "
                "varying scalars as 0-d tensors so one executable "
                "serves every value",
                f"cache of {name}"))
    return diags


def scan(obj: Any, fetch_list: Optional[list] = None) -> List[Diagnostic]:
    """Dispatching front door: accepts a Program, a StaticFunction, a
    Layer with a to_static forward, or a plain function."""
    if hasattr(obj, "blocks") and hasattr(obj, "global_block"):
        return scan_program(obj)
    if hasattr(obj, "_cache") and hasattr(obj, "_fn"):
        return sort_diagnostics(scan_static_function(obj))
    fwd = getattr(obj, "forward", None)
    if fwd is not None and hasattr(fwd, "_cache"):
        return sort_diagnostics(scan_static_function(fwd))
    if callable(obj):
        return sort_diagnostics(scan_function(obj))
    raise TypeError(
        f"cannot hazard-scan {type(obj).__name__}: expected a Program, "
        "StaticFunction, Layer, or function")
