# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.autograd analog: functional grad, PyLayer, backward.

Reference: /root/reference/python/paddle/autograd/py_layer.py:202 (PyLayer),
backward_mode.py (backward), and eager GeneralGrad
(/root/reference/paddle/fluid/eager/backward.cc:37) for the partial-grad API.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from ..core import tape as tape_mod
from ..core.dispatch import no_grad, no_grad_ctx, enable_grad_ctx, is_grad_enabled, set_grad_enabled  # noqa: F401
from ..core.tensor import Tensor

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
    "set_grad_enabled", "PyLayer", "PyLayerContext",
]

enable_grad = enable_grad_ctx


def backward(tensors: Sequence[Tensor], grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    tape_mod.run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None) -> List[Optional[Tensor]]:
    """Functional gradients of outputs w.r.t. inputs, without touching .grad.

    create_graph=True records the backward computation itself on the tape
    (reference: eager GeneralGrad + double-grad ops,
    paddle/fluid/eager/backward.cc:37), so the returned gradients can be
    differentiated again — gradient penalties, grad-of-grad checks.
    """
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    retain = retain_graph if retain_graph is not None else create_graph

    capture = {}
    capture_points = {}
    for t in inputs:
        capture[id(t)] = None
        if t._grad_node is not None:
            capture_points.setdefault(
                (id(t._grad_node), t._output_index), []).append(id(t))

    tape_mod.run_backward(outputs, grad_outputs, retain_graph=retain,
                          capture=capture, capture_points=capture_points,
                          create_graph=create_graph)

    results = []
    for t in inputs:
        c = capture[id(t)]
        if c is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have "
                    "been used in the graph (set allow_unused=True to allow)")
            results.append(None)
        elif isinstance(c, Tensor):  # create_graph: keep the grad's graph
            results.append(c)
        else:
            results.append(Tensor(c, stop_gradient=True))
    return results


class PyLayerContext:
    """ctx passed to PyLayer.forward/backward."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        # the reference API is a METHOD (`(x,) = ctx.saved_tensor()`,
        # /root/reference/python/paddle/autograd/py_layer.py:91) but
        # attribute-style access is a common user mistake the property
        # form also served — a callable tuple satisfies both.
        return _SavedTensors(self._saved)

    @property
    def saved_tensors(self):  # torch-style alias (property there)
        return _SavedTensors(self._saved)


class _SavedTensors(tuple):
    """Tuple of saved tensors that can also be CALLED (reference's
    ``ctx.saved_tensor()`` method form)."""

    def __call__(self):
        return tuple(self)


class _PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError("PyLayer subclasses are used via .apply(...)")


class PyLayer:
    """User-defined forward/backward, recorded as one node on the tape."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import dispatch

        ctx = PyLayerContext()
        with no_grad_ctx():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        record = dispatch.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if not record:
            return outs

        diff_inputs = [t for t in tensor_inputs
                       if not t.stop_gradient
                       and jnp.issubdtype(t._value.dtype, jnp.inexact)]

        def vjp_fn(cotangents):
            cots = (cotangents,) if not isinstance(cotangents, tuple) else cotangents
            grad_wrapped = [Tensor(c, stop_gradient=True) for c in cots]
            with no_grad_ctx():
                grads = cls.backward(ctx, *grad_wrapped)
            if isinstance(grads, Tensor) or grads is None:
                grads = (grads,)
            # backward returns one grad per *tensor input* of forward, in order
            by_input = {id(t): g for t, g in zip(tensor_inputs, grads)}
            vals = []
            for t in diff_inputs:
                g = by_input.get(id(t))
                vals.append(g._value if isinstance(g, Tensor) else jnp.zeros(
                    t.shape, t._value.dtype))
            return tuple(vals)

        def record_vjp(cot_tensors):
            # create_graph path: run the user backward WITH recording so
            # the produced grads carry their own graph.
            with enable_grad_ctx():
                grads = cls.backward(ctx, *cot_tensors)
            if isinstance(grads, Tensor) or grads is None:
                grads = (grads,)
            by_input = {id(t): g for t, g in zip(tensor_inputs, grads)}
            return [by_input.get(id(t)) if isinstance(
                by_input.get(id(t)), Tensor) else None
                for t in diff_inputs]

        node = tape_mod.GradNode(f"pylayer_{cls.__name__}", vjp_fn)
        node.record_vjp = record_vjp
        node.finalize(
            out_avals=[(tuple(o.shape), o._value.dtype) for o in out_list],
            single_output=single,
            inputs=diff_inputs,
        )
        for i, o in enumerate(out_list):
            o.stop_gradient = False
            o._grad_node = node
            o._output_index = i
        return outs


class LegacyPyLayer(PyLayer):
    pass


# ---------------------------------------------------------------------------
# functional transforms (reference: python/paddle/autograd/functional.py —
# vjp/jvp/Jacobian/Hessian built on double grad; here they ride jax's
# transforms directly, the TPU-native substrate the tape already lowers to)
# ---------------------------------------------------------------------------

def _wrap_fn(func):
    """Lift a Tensor->Tensor function to raw-array land for jax AD."""
    import jax

    def raw(*arrays):
        args = [Tensor(a) for a in arrays]
        out = func(*args)
        if isinstance(out, (list, tuple)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out

    return raw


def _vals(xs):
    if isinstance(xs, (list, tuple)):
        return [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                for x in xs]
    return [xs._value if isinstance(xs, Tensor) else jnp.asarray(xs)]


def _rewrap(vs):
    if isinstance(vs, (list, tuple)):
        out = tuple(Tensor(v) for v in vs)
        return out if len(out) != 1 else out[0]
    return Tensor(vs)


def vjp(func, xs, v=None):
    """(outputs, vjp_result) — reference autograd/functional.py vjp."""
    import jax

    vals = _vals(xs)
    out, pullback = jax.vjp(_wrap_fn(func), *vals)
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        cot = tuple(_vals(v)) if isinstance(v, (list, tuple)) else _vals(v)[0]
    grads = pullback(cot)
    return _rewrap(out), _rewrap(grads)


def jvp(func, xs, v=None):
    """(outputs, jvp_result) — forward-mode directional derivative."""
    import jax

    vals = _vals(xs)
    tangents = _vals(v) if v is not None else [jnp.ones_like(a)
                                               for a in vals]
    out, tangent_out = jax.jvp(_wrap_fn(func), tuple(vals), tuple(tangents))
    return _rewrap(out), _rewrap(tangent_out)


class Jacobian:
    """Dense Jacobian matrix (reference: autograd/functional.py Jacobian):
    rows = flattened outputs, columns = flattened inputs concatenated in
    order (the reference's matrix-view semantics for multi-input xs)."""

    def __init__(self, func, xs, is_batched=False):
        import math as _math

        import jax

        vals = _vals(xs)
        single_in = not isinstance(xs, (list, tuple))
        jac = jax.jacrev(_wrap_fn(func),
                         argnums=tuple(range(len(vals))))(*vals)
        if single_in:
            # natural out_shape + in_shape view
            self._jac = jnp.asarray(jac[0] if isinstance(jac, tuple)
                                    else jac)
        else:
            # flatten outputs to rows, concat flattened inputs as columns
            blocks = []
            for v, j in zip(vals, jac):
                j = jnp.asarray(j)
                out_size = _math.prod(j.shape[:j.ndim - v.ndim]) or 1
                blocks.append(j.reshape(out_size, v.size))
            self._jac = jnp.concatenate(blocks, axis=-1)
        self.is_batched = is_batched

    @property
    def shape(self):
        return jnp.shape(self._jac)

    def __getitem__(self, idx):
        return Tensor(jnp.asarray(self._jac)[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._jac)


class Hessian(Jacobian):
    """Hessian of a scalar-output function (reference: functional.Hessian):
    a [total_in, total_in] block matrix over the flattened inputs."""

    def __init__(self, func, xs, is_batched=False):
        import jax

        vals = _vals(xs)
        single_in = not isinstance(xs, (list, tuple))
        hes = jax.hessian(_wrap_fn(func),
                          argnums=tuple(range(len(vals))))(*vals)
        if single_in and len(vals) == 1:
            self._jac = jnp.asarray(hes[0][0]) if isinstance(hes, tuple) \
                else jnp.asarray(hes)
        else:
            sizes = [v.size for v in vals]
            rows = []
            for i in range(len(vals)):
                row = [jnp.asarray(hes[i][j]).reshape(sizes[i], sizes[j])
                       for j in range(len(vals))]
                rows.append(jnp.concatenate(row, axis=-1))
            self._jac = jnp.concatenate(rows, axis=0)
        self.is_batched = is_batched


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """Dense Jacobian Tensor(s) (reference dygraph autograd.jacobian)."""
    return Tensor(jnp.asarray(Jacobian(func, xs)._jac))


def hessian(func, xs, create_graph=False, allow_unused=False):
    return Tensor(jnp.asarray(Hessian(func, xs)._jac))


def no_grad_(func=None):
    """Decorator/context parity alias for no_grad (reference exports the
    decorator form as autograd.no_grad_)."""
    return no_grad(func) if func is not None else no_grad()


from . import backward_mode  # noqa: E402,F401
