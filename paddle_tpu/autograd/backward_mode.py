"""paddle.autograd.backward_mode (reference:
python/paddle/autograd/backward_mode.py — the multi-tensor backward
entry).  The engine is the tape in core/tape.py."""
from __future__ import annotations


def backward(tensors, grad_tensors=None, retain_graph=False):
    from . import backward as _backward

    return _backward(tensors, grad_tensors=grad_tensors,
                     retain_graph=retain_graph)
