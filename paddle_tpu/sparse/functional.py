"""paddle.sparse.functional (reference:
python/paddle/sparse/functional/__init__.py — relu / conv3d / subm_conv3d
/ max_pool3d).  Thin functional forms over the same sparse-native kernels
the ``paddle.sparse.nn`` layers use: the layers own parameters, these
take weight/bias as arguments."""
from __future__ import annotations

from ..core.tensor import Tensor, to_tensor
from . import Conv3D, MaxPool3D, SubmConv3D
from . import relu  # noqa: F401  (re-export; already functional)

__all__ = ["relu", "conv3d", "subm_conv3d", "max_pool3d"]


def _as_param(v):
    return v if isinstance(v, Tensor) or v is None else to_tensor(v)


def _functional_conv(cls, x, weight, bias, stride, padding, dilation,
                     groups, data_format):
    if data_format != "NDHWC":
        raise ValueError(
            f"sparse conv3d supports NDHWC only, got {data_format!r} "
            "(reference kernel layout, "
            "phi/kernels/sparse/gpu/convolution_kernel.cu)")
    weight = _as_param(weight)
    from ..nn.layer.conv import _ConvNd

    _t3 = _ConvNd._tuplize
    # bypass cls.__init__: it would CREATE parameters; the functional form
    # runs the same forward over caller-owned weight/bias
    layer = cls.__new__(cls)
    from ..nn.layer.layers import Layer as _Layer

    _Layer.__init__(layer)
    layer.kernel_size = tuple(int(k) for k in weight.shape[:3])
    layer.stride = _t3(stride, 3)
    layer.padding = _t3(padding, 3)
    layer.dilation = _t3(dilation, 3)
    layer.groups = groups
    layer.weight = weight
    layer.bias = _as_param(bias)
    return layer.forward(x)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC"):
    """Sparse conv3d; weight layout DHWIO (reference
    python/paddle/sparse/functional/conv.py conv3d)."""
    return _functional_conv(Conv3D, x, weight, bias, stride, padding,
                            dilation, groups, data_format)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC"):
    """Submanifold sparse conv3d: output index set == input index set."""
    return _functional_conv(SubmConv3D, x, weight, bias, stride, padding,
                            dilation, groups, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC"):
    """Sparse max pool over active sites (reference
    python/paddle/sparse/functional/pooling.py max_pool3d)."""
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only")
    return MaxPool3D(kernel_size, stride, padding)(x)
