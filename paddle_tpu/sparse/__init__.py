"""paddle.sparse (reference: python/paddle/sparse — COO/CSR tensors, sparse
ops; phi sparse kernels).

Backed by jax.experimental.sparse BCOO (XLA-lowered scatter/gather); CSR is
kept as a format view.  Dense fallbacks where BCOO lacks an op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor

try:
    from jax.experimental import sparse as jsparse

    _HAS_BCOO = True
except ImportError:  # pragma: no cover
    _HAS_BCOO = False

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "matmul", "masked_matmul", "relu",
           "nn"]


class SparseCooTensor:
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo, shape):
        self._bcoo = bcoo
        self._shape = tuple(shape)

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def nnz(self):
        return self._bcoo.nse

    @property
    def dtype(self):
        from ..core.dtype import convert_dtype

        return convert_dtype(self._bcoo.data.dtype)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    idx = indices.numpy() if isinstance(indices, Tensor) else np.asarray(indices)
    vals = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from ..core.dtype import to_np

        vals = vals.astype(to_np(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    crows_np = crows.numpy() if isinstance(crows, Tensor) else np.asarray(crows)
    cols_np = cols.numpy() if isinstance(cols, Tensor) else np.asarray(cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return sparse_coo_tensor(indices, values, shape, dtype)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def add(x: SparseCooTensor, y):
    if isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_add_any_sparse(x._bcoo, y._bcoo) if hasattr(
            jsparse, "bcoo_add_any_sparse") else \
            jsparse.BCOO.fromdense(x._bcoo.todense() + y._bcoo.todense())
        return SparseCooTensor(out, x._shape)
    return Tensor(x._bcoo.todense() + y._value)


def matmul(x, y):
    if isinstance(x, SparseCooTensor):
        dense_y = y._value if isinstance(y, Tensor) else y
        return Tensor(x._bcoo @ dense_y)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    out = x._value @ y._value
    dense_mask = (mask._bcoo.todense() != 0).astype(out.dtype)
    return SparseCooTensor(jsparse.BCOO.fromdense(out * dense_mask),
                           tuple(out.shape))


def relu(x: SparseCooTensor):
    return SparseCooTensor(
        jsparse.BCOO((jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
                     shape=x._shape), x._shape)


class nn:
    """paddle.sparse.nn subset (sparse conv is a planned kernel)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Conv3D:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                "sparse submanifold conv: planned Pallas kernel (reference "
                "phi/kernels/sparse/conv_kernel)")
