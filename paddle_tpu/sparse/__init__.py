# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.sparse (reference: python/paddle/sparse — COO/CSR tensors, sparse
ops; phi sparse kernels).

Backed by jax.experimental.sparse BCOO (XLA-lowered scatter/gather); CSR is
kept as a format view.  Dense fallbacks where BCOO lacks an op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..nn.layer.layers import Layer as _Layer

try:
    from jax.experimental import sparse as jsparse

    _HAS_BCOO = True
except ImportError:  # pragma: no cover
    _HAS_BCOO = False

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "BatchNorm", "Conv3D", "MaxPool3D", "ReLU", "SubmConv3D",
           "is_same_shape", "add", "matmul", "masked_matmul", "relu",
           "nn"]


class SparseCooTensor:
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h)."""

    _values_tensor = None  # tape-connected values (set by sparse layers)

    def __init__(self, bcoo, shape, values_tensor=None):
        self._bcoo = bcoo
        self._shape = tuple(shape)
        self._values_tensor = values_tensor

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        # the layer-produced Tensor carries the grad node: returning a
        # fresh wrapper would silently disconnect backward()
        if self._values_tensor is not None:
            return self._values_tensor
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def nnz(self):
        return self._bcoo.nse

    @property
    def dtype(self):
        from ..core.dtype import convert_dtype

        return convert_dtype(self._bcoo.data.dtype)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    idx = indices.numpy() if isinstance(indices, Tensor) else np.asarray(indices)
    vals = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from ..core.dtype import to_np

        vals = vals.astype(to_np(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    crows_np = crows.numpy() if isinstance(crows, Tensor) else np.asarray(crows)
    cols_np = cols.numpy() if isinstance(cols, Tensor) else np.asarray(cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return sparse_coo_tensor(indices, values, shape, dtype)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def add(x: SparseCooTensor, y):
    if isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_add_any_sparse(x._bcoo, y._bcoo) if hasattr(
            jsparse, "bcoo_add_any_sparse") else \
            jsparse.BCOO.fromdense(x._bcoo.todense() + y._bcoo.todense())
        return SparseCooTensor(out, x._shape)
    return Tensor(x._bcoo.todense() + y._value)


def matmul(x, y):
    if isinstance(x, SparseCooTensor):
        dense_y = y._value if isinstance(y, Tensor) else y
        return Tensor(x._bcoo @ dense_y)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    out = x._value @ y._value
    dense_mask = (mask._bcoo.todense() != 0).astype(out.dtype)
    return SparseCooTensor(jsparse.BCOO.fromdense(out * dense_mask),
                           tuple(out.shape))


def relu(x: SparseCooTensor):
    return SparseCooTensor(
        jsparse.BCOO((jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
                     shape=x._shape), x._shape)


def _dense_of(x):
    return x.to_dense()._value if isinstance(x, SparseCooTensor) else \
        (x._value if isinstance(x, Tensor) else jnp.asarray(x))


def _sparsify(dense, shape):
    # channel-dense layout (n_dense=1): data is [nnz, C], the shape the
    # per-site layers (BatchNorm) operate on.  Under a trace the stored-
    # element count must be static: bound it by the full volume (XLA
    # needs static shapes; the reference's DLPack path has dynamic nnz).
    nse = None
    if isinstance(dense, jax.core.Tracer):
        nse = 1
        for s in tuple(shape)[:-1]:
            nse *= int(s)
    return SparseCooTensor(
        jsparse.BCOO.fromdense(dense, n_dense=1, nse=nse), tuple(shape))


def _channel_dense_bcoo(x):
    """BCOO with a dense trailing channel dim ([nnz, C] data)."""
    if x._bcoo.n_dense >= 1:
        return x._bcoo
    return jsparse.BCOO.fromdense(x._bcoo.todense(), n_dense=1)


def _active_mask(x):
    """[N, D, H, W, 1] bool mask of the INDEX SET (not the values —
    explicitly-stored zeros are active sites in submanifold semantics)."""
    bcoo = _channel_dense_bcoo(x)
    idx = bcoo.indices  # [nnz, ndim_sparse]
    mask = jnp.zeros(x._shape[:idx.shape[1]] + (1,), bool)
    return mask.at[tuple(idx[:, i] for i in range(idx.shape[1]))
                   + (0,)].set(True)


class Conv3D(_Layer):
    """Sparse 3-D conv on NDHWC COO tensors (reference:
    paddle.sparse.nn.Conv3D over
    phi/kernels/sparse/gpu/convolution_kernel.cu).  Sparse-NATIVE in
    eager mode (VERDICT r3 #5): the output site set is the union of
    stride-mapped shifted input sites (computed host-side from the
    concrete indices, the rulebook-build step), then a gather-GEMM over
    it — no todense.  Under a jit trace the output nnz would be a
    data-dependent shape, so the traced path lowers dense (the same
    static-shape tension as nonzero(); the reference's DLPack path has
    dynamic shapes to spend).  A real nn.Layer, so parameters
    register/train/checkpoint."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None):
        super().__init__()
        from ..nn.layer.conv import _ConvNd

        _t3 = _ConvNd._tuplize
        self.kernel_size = _t3(kernel_size, 3)
        self.stride = _t3(stride, 3)
        self.padding = _t3(padding, 3)
        self.dilation = _t3(dilation, 3)
        self.groups = groups
        # kernel layout DHWIO (lax conv_general_dilated NDHWC convention)
        self.weight = self.create_parameter(
            list(self.kernel_size) + [in_channels // groups, out_channels])
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], is_bias=True)

    def _conv(self, dense):
        out = jax.lax.conv_general_dilated(
            dense, self.weight._value,
            window_strides=self.stride,
            padding=[(p, p) for p in self.padding],
            rhs_dilation=self.dilation,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            feature_group_count=self.groups)
        if self.bias is not None:
            out = out + self.bias._value
        return out

    def _out_spatial(self, in_spatial):
        return tuple(
            (s + 2 * p - dl * (k - 1) - 1) // st + 1
            for s, p, dl, k, st in zip(in_spatial, self.padding,
                                       self.dilation, self.kernel_size,
                                       self.stride))

    def _out_sites(self, in_idx, in_spatial):
        """Union of shifted input sites mapped through the stride — the
        output index set (host numpy; the reference builds the same set
        into its rulebook hash table)."""
        import numpy as np

        outs = self._out_spatial(in_spatial)
        n = in_idx[:, :1]
        sp = in_idx[:, 1:]
        cand = []
        for kd in range(self.kernel_size[0]):
            for kh in range(self.kernel_size[1]):
                for kw in range(self.kernel_size[2]):
                    off = np.array([kd * self.dilation[0],
                                    kh * self.dilation[1],
                                    kw * self.dilation[2]])
                    num = sp + np.array(self.padding) - off
                    div = num // np.array(self.stride)
                    ok = ((num % np.array(self.stride) == 0)
                          & (div >= 0) & (div < np.array(outs))).all(1)
                    if ok.any():
                        cand.append(np.concatenate([n[ok], div[ok]], 1))
        if not cand:
            return np.zeros((0, 4), np.int32), outs
        allc = np.concatenate(cand, 0)
        lin = ((allc[:, 0] * outs[0] + allc[:, 1]) * outs[1]
               + allc[:, 2]) * outs[2] + allc[:, 3]
        uniq = np.unique(lin)  # sorted => lexicographic (n, d, h, w)
        w = uniq % outs[2]
        rest = uniq // outs[2]
        h = rest % outs[1]
        rest = rest // outs[1]
        d = rest % outs[0]
        n_ = rest // outs[0]
        return np.stack([n_, d, h, w], 1).astype(np.int32), outs

    def forward(self, x):
        bcoo = _channel_dense_bcoo(x)
        if isinstance(bcoo.indices, jax.core.Tracer):
            # data-dependent output nnz can't trace: dense fallback,
            # masked to the reachable site set (ones-kernel conv over the
            # occupancy mask) so traced values match the eager native
            # path — bias only lands on active sites, like the reference
            out = self._conv(_dense_of(x))
            occ = _active_mask(x).astype(out.dtype)
            reach = jax.lax.conv_general_dilated(
                occ, jnp.ones(tuple(self.kernel_size) + (1, 1), out.dtype),
                window_strides=self.stride,
                padding=[(p, p) for p in self.padding],
                rhs_dilation=self.dilation,
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
            out = jnp.where(reach > 0, out, 0)
            return _sparsify(out, out.shape)
        import numpy as np

        from ..core.dispatch import apply as _apply

        in_idx = np.asarray(bcoo.indices)  # host copy: rulebook build
        out_idx_np, outs = self._out_sites(in_idx, tuple(x._shape[1:4]))
        out_shape = (x._shape[0],) + outs + (int(self.weight.shape[-1]),)
        out_idx = jnp.asarray(out_idx_np)

        def _fn(data, w, *rest):
            b = rest[0] if rest else None
            return _sparse_conv_native(
                data, bcoo.indices, out_idx, w, b,
                in_shape=tuple(x._shape),
                kernel_size=tuple(self.kernel_size),
                stride=tuple(self.stride), padding=tuple(self.padding),
                dilation=tuple(self.dilation), groups=self.groups)

        args = [Tensor(bcoo.data), self.weight]
        if self.bias is not None:
            args.append(self.bias)
        out = _apply("sparse_conv3d", _fn, *args)
        return SparseCooTensor(
            jsparse.BCOO((out._value, out_idx), shape=out_shape),
            out_shape, values_tensor=out)


import functools as _functools


@_functools.partial(
    jax.jit, static_argnames=("in_shape", "kernel_size", "stride",
                              "padding", "dilation", "groups"))
def _sparse_conv_native(data, in_idx, out_idx, weight, bias, in_shape,
                        kernel_size, stride, padding, dilation, groups):
    """Sparse-NATIVE conv: gather-GEMM, no todense (reference:
    phi/kernels/sparse/gpu/convolution_kernel.cu's rulebook
    gather/scatter, re-designed TPU-first).

    A dense int32 site-id volume replaces the reference's hash-table
    rulebook (O(N*D*H*W) int32 — ~C times smaller than the dense feature
    volume); for each OUTPUT site the K kernel-offset neighbor rows are
    gathered from the input and the K gathers fold into ONE
    [m, K*Cin] x [K*Cin, Cout] matmul that the MXU tiles directly.  The
    submanifold case is out_idx == in_idx with stride 1 / same padding;
    the general (strided / output-growing) case passes the output site
    set computed by the caller.  All ops are jnp (jit/grad-compatible).

    data [nnz, Cin]; in_idx [nnz, 4] int (n, d, h, w); out_idx [m, 4]
    int over OUTPUT coords; weight [kD, kH, kW, Cin/g, Cout];
    returns [m, Cout]."""
    N, D, H, W = (int(s) for s in in_shape[:4])
    nnz, Cin = data.shape
    kD, kH, kW = kernel_size
    K = kD * kH * kW
    Cout = weight.shape[-1]
    in_idx = in_idx.astype(jnp.int32)
    out_idx = out_idx.astype(jnp.int32)
    m = out_idx.shape[0]

    vol = jnp.full((N, D, H, W), -1, jnp.int32)
    vol = vol.at[in_idx[:, 0], in_idx[:, 1], in_idx[:, 2],
                 in_idx[:, 3]].set(jnp.arange(nnz, dtype=jnp.int32))

    stride_v = jnp.asarray(stride, jnp.int32)
    pad_v = jnp.asarray(padding, jnp.int32)
    dil = dilation
    hi = jnp.asarray([D - 1, H - 1, W - 1], jnp.int32)
    base = out_idx[:, 1:] * stride_v - pad_v      # [m, 3] input origin
    gathered = []
    for kd in range(kD):
        for kh in range(kH):
            for kw in range(kW):
                off = jnp.asarray([kd * dil[0], kh * dil[1], kw * dil[2]],
                                  jnp.int32)
                coords = base + off
                inb = ((coords >= 0) & (coords <= hi)).all(-1)
                cc = jnp.clip(coords, 0, hi)
                nb = vol[out_idx[:, 0], cc[:, 0], cc[:, 1], cc[:, 2]]
                valid = inb & (nb >= 0)
                rows = data[jnp.clip(nb, 0, max(nnz - 1, 0))]
                gathered.append(jnp.where(valid[:, None], rows, 0))
    g = jnp.stack(gathered, 1)                      # [m, K, Cin]
    if groups == 1:
        out = g.reshape(m, K * Cin) @ weight.reshape(K * Cin, Cout)
    else:
        cg, og = Cin // groups, Cout // groups
        wg = weight.reshape(K, cg, Cout)
        outs = []
        for gi in range(groups):
            gg = g[:, :, gi * cg:(gi + 1) * cg].reshape(m, K * cg)
            wgi = wg[:, :, gi * og:(gi + 1) * og].reshape(K * cg, og)
            outs.append(gg @ wgi)
        out = jnp.concatenate(outs, -1)
    if bias is not None:
        out = out + bias
    return out


class SubmConv3D(Conv3D):
    """Submanifold conv: the OUTPUT index set equals the input's
    (reference SubmConv3D over
    phi/kernels/sparse/gpu/convolution_kernel.cu; requires stride 1 /
    same-size output).  The pattern comes from the INDEX SET, so sites
    storing all-zero features stay active across layers.  Computes
    sparse-natively (gather-GEMM, no todense) — VERDICT r2 #4."""

    def forward(self, x):
        for i in range(3):
            if self.stride[i] != 1:
                raise ValueError("SubmConv3D requires stride 1")
            if self.padding[i] != (self.kernel_size[i] - 1) // 2 \
                    * self.dilation[i]:
                raise ValueError(
                    "SubmConv3D requires same-padding "
                    f"((k-1)//2*dilation), got padding={self.padding}")
        bcoo = _channel_dense_bcoo(x)
        from ..core.dispatch import apply as _apply

        idx = bcoo.indices
        out_shape = tuple(x._shape[:4]) + (self.weight.shape[-1],)

        def _fn(data, w, *rest):
            b = rest[0] if rest else None
            return _sparse_conv_native(
                data, idx, idx, w, b, in_shape=tuple(x._shape),
                kernel_size=tuple(self.kernel_size),
                stride=(1, 1, 1), padding=tuple(self.padding),
                dilation=tuple(self.dilation), groups=self.groups)

        args = [Tensor(bcoo.data), self.weight]
        if self.bias is not None:
            args.append(self.bias)
        out = _apply("subm_conv3d", _fn, *args)
        return SparseCooTensor(
            jsparse.BCOO((out._value, idx), shape=out_shape), out_shape,
            values_tensor=out)


class BatchNorm(_Layer):
    """BatchNorm over the channel dim of ACTIVE sites only (reference
    paddle.sparse.nn.BatchNorm: statistics exclude the empty space)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        super().__init__()
        from ..nn import initializer as I

        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        self._mean = jnp.zeros(num_features)
        self._var = jnp.ones(num_features)

    def forward(self, x):
        bcoo = _channel_dense_bcoo(x)
        data = bcoo.data  # [nnz, C] — active sites only
        if self.training:
            mean = jnp.mean(data, axis=0)
            var = jnp.var(data, axis=0)
            self._mean = self.momentum * self._mean + (1 - self.momentum) \
                * mean
            self._var = self.momentum * self._var + (1 - self.momentum) * var
        else:
            mean, var = self._mean, self._var
        norm = (data - mean) * jax.lax.rsqrt(var + self.epsilon)
        new = norm * self.weight._value + self.bias._value
        return SparseCooTensor(jsparse.BCOO((new, bcoo.indices),
                                            shape=x._shape), x._shape)


class MaxPool3D(_Layer):
    """Max over ACTIVE sites only: empty space must not contribute its
    implicit zero (which would beat negative features)."""

    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        from ..nn.layer.conv import _ConvNd

        _t3 = _ConvNd._tuplize
        self.kernel_size = _t3(kernel_size, 3)
        self.stride = _t3(stride if stride is not None else kernel_size, 3)
        self.padding = _t3(padding, 3)

    def forward(self, x):
        dense = _dense_of(x)
        active = _active_mask(x)
        guarded = jnp.where(active, dense, -jnp.inf)
        win = (1,) + self.kernel_size + (1,)
        strd = (1,) + self.stride + (1,)
        pads = [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)]
        out = jax.lax.reduce_window(guarded, -jnp.inf, jax.lax.max, win,
                                    strd, pads)
        out = jnp.where(jnp.isfinite(out), out, 0.0)  # all-empty windows
        return _sparsify(out, out.shape)


class ReLU(_Layer):
    def forward(self, x):
        return relu(x)


class nn_namespace:
    """paddle.sparse.nn (reference: python/paddle/sparse/nn/)."""

    ReLU = ReLU
    Conv3D = Conv3D
    SubmConv3D = SubmConv3D
    BatchNorm = BatchNorm
    MaxPool3D = MaxPool3D


nn = nn_namespace

from . import functional  # noqa: E402,F401  (needs the classes above)
