"""paddle.sparse (reference: python/paddle/sparse — COO/CSR tensors, sparse
ops; phi sparse kernels).

Backed by jax.experimental.sparse BCOO (XLA-lowered scatter/gather); CSR is
kept as a format view.  Dense fallbacks where BCOO lacks an op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..nn.layer.layers import Layer as _Layer

try:
    from jax.experimental import sparse as jsparse

    _HAS_BCOO = True
except ImportError:  # pragma: no cover
    _HAS_BCOO = False

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "BatchNorm", "Conv3D", "MaxPool3D", "ReLU", "SubmConv3D",
           "is_same_shape", "add", "matmul", "masked_matmul", "relu",
           "nn"]


class SparseCooTensor:
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h)."""

    _values_tensor = None  # tape-connected values (set by sparse layers)

    def __init__(self, bcoo, shape, values_tensor=None):
        self._bcoo = bcoo
        self._shape = tuple(shape)
        self._values_tensor = values_tensor

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        # the layer-produced Tensor carries the grad node: returning a
        # fresh wrapper would silently disconnect backward()
        if self._values_tensor is not None:
            return self._values_tensor
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def nnz(self):
        return self._bcoo.nse

    @property
    def dtype(self):
        from ..core.dtype import convert_dtype

        return convert_dtype(self._bcoo.data.dtype)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    idx = indices.numpy() if isinstance(indices, Tensor) else np.asarray(indices)
    vals = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from ..core.dtype import to_np

        vals = vals.astype(to_np(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    crows_np = crows.numpy() if isinstance(crows, Tensor) else np.asarray(crows)
    cols_np = cols.numpy() if isinstance(cols, Tensor) else np.asarray(cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return sparse_coo_tensor(indices, values, shape, dtype)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def add(x: SparseCooTensor, y):
    if isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_add_any_sparse(x._bcoo, y._bcoo) if hasattr(
            jsparse, "bcoo_add_any_sparse") else \
            jsparse.BCOO.fromdense(x._bcoo.todense() + y._bcoo.todense())
        return SparseCooTensor(out, x._shape)
    return Tensor(x._bcoo.todense() + y._value)


def matmul(x, y):
    if isinstance(x, SparseCooTensor):
        dense_y = y._value if isinstance(y, Tensor) else y
        return Tensor(x._bcoo @ dense_y)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    out = x._value @ y._value
    dense_mask = (mask._bcoo.todense() != 0).astype(out.dtype)
    return SparseCooTensor(jsparse.BCOO.fromdense(out * dense_mask),
                           tuple(out.shape))


def relu(x: SparseCooTensor):
    return SparseCooTensor(
        jsparse.BCOO((jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
                     shape=x._shape), x._shape)


def _dense_of(x):
    return x.to_dense()._value if isinstance(x, SparseCooTensor) else \
        (x._value if isinstance(x, Tensor) else jnp.asarray(x))


def _sparsify(dense, shape):
    # channel-dense layout (n_dense=1): data is [nnz, C], the shape the
    # per-site layers (BatchNorm) operate on
    return SparseCooTensor(jsparse.BCOO.fromdense(dense, n_dense=1),
                           tuple(shape))


def _channel_dense_bcoo(x):
    """BCOO with a dense trailing channel dim ([nnz, C] data)."""
    if x._bcoo.n_dense >= 1:
        return x._bcoo
    return jsparse.BCOO.fromdense(x._bcoo.todense(), n_dense=1)


def _active_mask(x):
    """[N, D, H, W, 1] bool mask of the INDEX SET (not the values —
    explicitly-stored zeros are active sites in submanifold semantics)."""
    bcoo = _channel_dense_bcoo(x)
    idx = bcoo.indices  # [nnz, ndim_sparse]
    mask = jnp.zeros(x._shape[:idx.shape[1]] + (1,), bool)
    return mask.at[tuple(idx[:, i] for i in range(idx.shape[1]))
                   + (0,)].set(True)


class Conv3D(_Layer):
    """Sparse 3-D conv on NDHWC COO tensors (reference:
    paddle.sparse.nn.Conv3D over phi/kernels/sparse/conv_kernel).
    Dense-lowered: XLA tiles the conv on the MXU; the gather/GEMM/
    scatter kernel is the Pallas optimization path, the semantics live
    here.  A real nn.Layer, so parameters register/train/checkpoint."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None):
        super().__init__()
        from ..nn.layer.conv import _ConvNd

        _t3 = _ConvNd._tuplize
        self.kernel_size = _t3(kernel_size, 3)
        self.stride = _t3(stride, 3)
        self.padding = _t3(padding, 3)
        self.dilation = _t3(dilation, 3)
        self.groups = groups
        # kernel layout DHWIO (lax conv_general_dilated NDHWC convention)
        self.weight = self.create_parameter(
            list(self.kernel_size) + [in_channels // groups, out_channels])
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], is_bias=True)

    def _conv(self, dense):
        out = jax.lax.conv_general_dilated(
            dense, self.weight._value,
            window_strides=self.stride,
            padding=[(p, p) for p in self.padding],
            rhs_dilation=self.dilation,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            feature_group_count=self.groups)
        if self.bias is not None:
            out = out + self.bias._value
        return out

    def forward(self, x):
        out = self._conv(_dense_of(x))
        return _sparsify(out, out.shape)


import functools as _functools


@_functools.partial(
    jax.jit, static_argnames=("shape", "kernel_size", "dilation", "groups"))
def _subm_conv_native(data, idx, weight, bias, shape, kernel_size,
                      dilation, groups):
    """Sparse-NATIVE submanifold conv: gather-GEMM-scatter, no todense
    (reference: phi/kernels/sparse/gpu/convolution_kernel.cu's rulebook
    gather/scatter, re-designed TPU-first).

    A dense int32 site-id volume replaces the reference's hash-table
    rulebook (O(N*D*H*W) int32 — ~C times smaller than the dense feature
    volume); per kernel-offset neighbor rows are gathered and the K
    gathers fold into ONE [nnz, K*Cin] x [K*Cin, Cout] matmul that the
    MXU tiles directly.  All ops are jnp (jit/grad-compatible).

    data [nnz, Cin]; idx [nnz, 4] int (n, d, h, w); weight
    [kD, kH, kW, Cin/g, Cout]; returns [nnz, Cout]."""
    N, D, H, W = (int(s) for s in shape[:4])
    nnz, Cin = data.shape
    kD, kH, kW = kernel_size
    K = kD * kH * kW
    Cout = weight.shape[-1]
    idx = idx.astype(jnp.int32)

    vol = jnp.full((N, D, H, W), -1, jnp.int32)
    vol = vol.at[idx[:, 0], idx[:, 1], idx[:, 2], idx[:, 3]].set(
        jnp.arange(nnz, dtype=jnp.int32))

    center = ((kD - 1) // 2, (kH - 1) // 2, (kW - 1) // 2)
    hi = jnp.asarray([D - 1, H - 1, W - 1], jnp.int32)
    gathered = []
    for kd in range(kD):
        for kh in range(kH):
            for kw in range(kW):
                off = jnp.asarray(
                    [(kd - center[0]) * dilation[0],
                     (kh - center[1]) * dilation[1],
                     (kw - center[2]) * dilation[2]], jnp.int32)
                coords = idx[:, 1:] + off
                inb = ((coords >= 0) & (coords <= hi)).all(-1)
                cc = jnp.clip(coords, 0, hi)
                nb = vol[idx[:, 0], cc[:, 0], cc[:, 1], cc[:, 2]]
                valid = inb & (nb >= 0)
                rows = data[jnp.clip(nb, 0, max(nnz - 1, 0))]
                gathered.append(jnp.where(valid[:, None], rows, 0))
    g = jnp.stack(gathered, 1)                      # [nnz, K, Cin]
    if groups == 1:
        out = g.reshape(nnz, K * Cin) @ weight.reshape(K * Cin, Cout)
    else:
        cg, og = Cin // groups, Cout // groups
        wg = weight.reshape(K, cg, Cout)
        outs = []
        for gi in range(groups):
            gg = g[:, :, gi * cg:(gi + 1) * cg].reshape(nnz, K * cg)
            wgi = wg[:, :, gi * og:(gi + 1) * og].reshape(K * cg, og)
            outs.append(gg @ wgi)
        out = jnp.concatenate(outs, -1)
    if bias is not None:
        out = out + bias
    return out


class SubmConv3D(Conv3D):
    """Submanifold conv: the OUTPUT index set equals the input's
    (reference SubmConv3D over
    phi/kernels/sparse/gpu/convolution_kernel.cu; requires stride 1 /
    same-size output).  The pattern comes from the INDEX SET, so sites
    storing all-zero features stay active across layers.  Computes
    sparse-natively (gather-GEMM, no todense) — VERDICT r2 #4."""

    def forward(self, x):
        for i in range(3):
            if self.stride[i] != 1:
                raise ValueError("SubmConv3D requires stride 1")
            if self.padding[i] != (self.kernel_size[i] - 1) // 2 \
                    * self.dilation[i]:
                raise ValueError(
                    "SubmConv3D requires same-padding "
                    f"((k-1)//2*dilation), got padding={self.padding}")
        bcoo = _channel_dense_bcoo(x)
        from ..core.dispatch import apply as _apply

        idx = bcoo.indices
        out_shape = tuple(x._shape[:4]) + (self.weight.shape[-1],)

        def _fn(data, w, *rest):
            b = rest[0] if rest else None
            return _subm_conv_native(
                data, idx, w, b, shape=tuple(x._shape),
                kernel_size=tuple(self.kernel_size),
                dilation=tuple(self.dilation), groups=self.groups)

        args = [Tensor(bcoo.data), self.weight]
        if self.bias is not None:
            args.append(self.bias)
        out = _apply("subm_conv3d", _fn, *args)
        return SparseCooTensor(
            jsparse.BCOO((out._value, idx), shape=out_shape), out_shape,
            values_tensor=out)


class BatchNorm(_Layer):
    """BatchNorm over the channel dim of ACTIVE sites only (reference
    paddle.sparse.nn.BatchNorm: statistics exclude the empty space)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        super().__init__()
        from ..nn import initializer as I

        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        self._mean = jnp.zeros(num_features)
        self._var = jnp.ones(num_features)

    def forward(self, x):
        bcoo = _channel_dense_bcoo(x)
        data = bcoo.data  # [nnz, C] — active sites only
        if self.training:
            mean = jnp.mean(data, axis=0)
            var = jnp.var(data, axis=0)
            self._mean = self.momentum * self._mean + (1 - self.momentum) \
                * mean
            self._var = self.momentum * self._var + (1 - self.momentum) * var
        else:
            mean, var = self._mean, self._var
        norm = (data - mean) * jax.lax.rsqrt(var + self.epsilon)
        new = norm * self.weight._value + self.bias._value
        return SparseCooTensor(jsparse.BCOO((new, bcoo.indices),
                                            shape=x._shape), x._shape)


class MaxPool3D(_Layer):
    """Max over ACTIVE sites only: empty space must not contribute its
    implicit zero (which would beat negative features)."""

    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        from ..nn.layer.conv import _ConvNd

        _t3 = _ConvNd._tuplize
        self.kernel_size = _t3(kernel_size, 3)
        self.stride = _t3(stride if stride is not None else kernel_size, 3)
        self.padding = _t3(padding, 3)

    def forward(self, x):
        dense = _dense_of(x)
        active = _active_mask(x)
        guarded = jnp.where(active, dense, -jnp.inf)
        win = (1,) + self.kernel_size + (1,)
        strd = (1,) + self.stride + (1,)
        pads = [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)]
        out = jax.lax.reduce_window(guarded, -jnp.inf, jax.lax.max, win,
                                    strd, pads)
        out = jnp.where(jnp.isfinite(out), out, 0.0)  # all-empty windows
        return _sparsify(out, out.shape)


class ReLU(_Layer):
    def forward(self, x):
        return relu(x)


class nn_namespace:
    """paddle.sparse.nn (reference: python/paddle/sparse/nn/)."""

    ReLU = ReLU
    Conv3D = Conv3D
    SubmConv3D = SubmConv3D
    BatchNorm = BatchNorm
    MaxPool3D = MaxPool3D


nn = nn_namespace
