# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.text (reference: python/paddle/text/ — NLP datasets) + a host-side
tokenizer (the reference's in-graph faster_tokenizer_op,
paddle/fluid/operators/string/faster_tokenizer_op.cc:525, becomes host
preprocessing feeding infeed on TPU)."""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "WMT16", "Conll05st",
           "Movielens",
           "BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "ViterbiDecoder", "viterbi_decode"]


class _LocalFileDataset(Dataset):
    name = "dataset"

    def __init__(self, data_file=None, mode="train", **kwargs):
        if data_file is None:
            raise ValueError(
                f"no network egress: pass data_file with a local copy of "
                f"{self.name}")
        self.data_file = data_file
        self.mode = mode
        self._load()

    def _load(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class UCIHousing(_LocalFileDataset):
    name = "uci_housing (housing.data)"

    def _load(self):
        raw = np.loadtxt(self.data_file)
        x = raw[:, :-1].astype(np.float32)
        y = raw[:, -1:].astype(np.float32)
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        n = int(len(x) * 0.8)
        if self.mode == "train":
            self.samples = list(zip(x[:n], y[:n]))
        else:
            self.samples = list(zip(x[n:], y[n:]))


class Imdb(_LocalFileDataset):
    name = "imdb (aclImdb tarball)"

    def _load(self):
        import re
        import tarfile

        pattern = re.compile(
            rf"aclImdb/{self.mode}/(pos|neg)/.*\.txt$")
        self.samples = []
        with tarfile.open(self.data_file) as tar:
            for member in tar.getmembers():
                m = pattern.match(member.name)
                if m:
                    text = tar.extractfile(member).read().decode(
                        "utf-8", "ignore")
                    label = 1 if m.group(1) == "pos" else 0
                    self.samples.append((text, np.asarray(label, np.int64)))


class Imikolov(_LocalFileDataset):
    """N-gram windows over the PTB-style imikolov corpus (reference:
    python/paddle/text/datasets/imikolov.py).  data_file: a text file of
    whitespace-tokenized sentences; yields (context..., target) id tuples
    over a min-frequency vocabulary like the reference."""

    name = "imikolov (simple-examples text file)"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, **kwargs):
        if str(data_type).upper() not in ("NGRAM", "SEQ"):
            raise ValueError(f"data_type must be NGRAM or SEQ, got "
                             f"{data_type!r}")
        self.data_type = data_type
        self.window_size = int(window_size)
        self.min_word_freq = int(min_word_freq)
        super().__init__(data_file=data_file, mode=mode, **kwargs)

    def _load(self):
        from collections import Counter

        lines = []
        with open(self.data_file, "r", encoding="utf-8",
                  errors="ignore") as f:
            for line in f:
                toks = line.strip().split()
                if toks:
                    lines.append(toks)
        freq = Counter(w for toks in lines for w in toks)
        vocab = {"<unk>": 0, "<s>": 1, "<e>": 2}
        for w, c in sorted(freq.items()):
            if c >= self.min_word_freq and w not in vocab:
                vocab[w] = len(vocab)
        self.word_idx = vocab
        unk = vocab["<unk>"]
        self.samples = []
        for toks in lines:
            ids = [vocab["<s>"]] + [vocab.get(w, unk) for w in toks]                 + [vocab["<e>"]]
            if self.data_type.upper() == "NGRAM":
                n = self.window_size
                for i in range(len(ids) - n + 1):
                    self.samples.append(tuple(
                        np.asarray(v, np.int64) for v in ids[i:i + n]))
            else:  # SEQ: (input, shifted-target) pairs
                self.samples.append(
                    (np.asarray(ids[:-1], np.int64),
                     np.asarray(ids[1:], np.int64)))


_WMT_UNK_IDX = 2  # reference wmt14.py UNK_IDX convention (<s>=0, <e>=1)


class WMT14(_LocalFileDataset):
    """Preprocessed WMT14 translation pairs (reference:
    python/paddle/text/datasets/wmt14.py:120 — tarball holding
    ``src.dict``/``trg.dict`` members (one token per line, id = line
    number) and ``{mode}/{mode}`` members of tab-separated
    "source<TAB>target" lines).  Yields (src_ids, trg_ids,
    trg_ids_next): source wrapped in <s>/<e>, target with leading <s>,
    next-target with trailing <e>; pairs longer than 80 tokens are
    dropped like the reference."""

    name = "wmt14 (preprocessed tgz: src.dict/trg.dict + mode/mode)"

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 **kwargs):
        self.dict_size = int(dict_size)
        super().__init__(data_file=data_file, mode=mode, **kwargs)

    def _read_dict(self, fobj):
        d: Dict[str, int] = {}
        for i, line in enumerate(fobj):
            if 0 < self.dict_size <= i:
                break
            d[line.decode("utf-8", "ignore").strip()] = i
        return d

    def _load(self):
        import tarfile

        with tarfile.open(self.data_file) as tar:
            names = tar.getnames()

            def only(suffix):
                match = [n for n in names if n.endswith(suffix)]
                if len(match) != 1:
                    raise ValueError(
                        f"{self.name}: expected exactly one member ending "
                        f"{suffix!r}, found {match}")
                return match[0]

            self.src_dict = self._read_dict(tar.extractfile(
                only("src.dict")))
            self.trg_dict = self._read_dict(tar.extractfile(
                only("trg.dict")))
            sd, td = self.src_dict, self.trg_dict
            self.samples = []
            data_suffix = f"{self.mode}/{self.mode}"
            for n in names:
                if not n.endswith(data_suffix):
                    continue
                for line in tar.extractfile(n):
                    parts = line.decode("utf-8", "ignore").strip() \
                        .split("\t")
                    if len(parts) != 2:
                        continue
                    src = [sd.get(w, _WMT_UNK_IDX)
                           for w in ["<s>"] + parts[0].split() + ["<e>"]]
                    trg = [td.get(w, _WMT_UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.samples.append(
                        (np.asarray(src, np.int64),
                         np.asarray([td["<s>"]] + trg, np.int64),
                         np.asarray(trg + [td["<e>"]], np.int64)))
        if not self.samples:
            raise ValueError(
                f"{self.name}: no '{self.mode}/{self.mode}' pairs found "
                f"in {self.data_file}")

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(_LocalFileDataset):
    """ACL2016 Multi30K en↔de pairs (reference:
    python/paddle/text/datasets/wmt16.py — tarball member
    ``wmt16/{mode}`` of tab-separated "en<TAB>de" lines; vocabularies are
    BUILT from the ``wmt16/train`` corpus by frequency with
    <s>/<e>/<unk> prepended, unlike WMT14's shipped dict members).
    ``lang`` selects the source column; dict sizes of -1 keep every
    word."""

    name = "wmt16 (tarball with wmt16/{train,test,val} members)"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", **kwargs):
        if mode not in ("train", "test", "val"):
            raise ValueError(
                f"mode should be 'train', 'test' or 'val', got {mode!r}")
        if lang not in ("en", "de"):
            raise ValueError(f"lang should be 'en' or 'de', got {lang!r}")
        self.lang = lang
        self.src_dict_size = int(src_dict_size)
        self.trg_dict_size = int(trg_dict_size)
        super().__init__(data_file=data_file, mode=mode, **kwargs)

    def _build_dict(self, tar, col, size):
        from collections import Counter

        freq = Counter()
        for line in tar.extractfile("wmt16/train"):
            parts = line.decode("utf-8", "ignore").strip().split("\t")
            if len(parts) == 2:
                freq.update(parts[col].split())
        d = {"<s>": 0, "<e>": 1, "<unk>": 2}
        # frequency order like the reference; ties broken by word for
        # run-to-run determinism
        for w, _ in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])):
            if 0 < size <= len(d):
                break
            if w not in d:
                d[w] = len(d)
        return d

    def _load(self):
        import tarfile

        src_col = 0 if self.lang == "en" else 1
        with tarfile.open(self.data_file) as tar:
            self.src_dict = self._build_dict(tar, src_col,
                                             self.src_dict_size)
            self.trg_dict = self._build_dict(tar, 1 - src_col,
                                             self.trg_dict_size)
            sd, td = self.src_dict, self.trg_dict
            self.samples = []
            for line in tar.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode("utf-8", "ignore").strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [0] + [sd.get(w, 2)
                             for w in parts[src_col].split()] + [1]
                trg = [td.get(w, 2) for w in parts[1 - src_col].split()]
                self.samples.append(
                    (np.asarray(src, np.int64),
                     np.asarray([0] + trg, np.int64),
                     np.asarray(trg + [1], np.int64)))

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d


class Conll05st(_LocalFileDataset):
    """CoNLL-2005 SRL (reference: python/paddle/text/datasets/conll05.py
    — tarball with ``.../words/test.wsj.words.gz`` and
    ``.../props/test.wsj.props.gz`` members plus word/verb/label dict
    files).  Props bracket notation ``(A0*``/``*``/``*)`` expands to
    B-/I-/O tags; each predicate column yields one sample of
    (word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
    label_idx) arrays, the reference's 9-slot SRL layout."""

    name = "conll05st (tarball + word/verb/target dict files)"

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="test",
                 **kwargs):
        if not (word_dict_file and verb_dict_file and target_dict_file):
            raise ValueError(
                "no network egress: pass word_dict_file, verb_dict_file "
                "and target_dict_file with local copies")
        self.word_dict = self._read_dict(word_dict_file)
        self.predicate_dict = self._read_dict(verb_dict_file)
        self.label_dict = self._read_label_dict(target_dict_file)
        super().__init__(data_file=data_file, mode=mode, **kwargs)

    @staticmethod
    def _read_dict(path):
        with open(path, "r", encoding="utf-8") as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _read_label_dict(path):
        tags = set()
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d = {}
        # B/I interleaved, O last (reference layout); sorted for
        # determinism — the reference iterates a raw set, whose order is
        # hash-randomized across interpreter runs
        for tag in sorted(tags):
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    @staticmethod
    def _expand_props(col):
        """One predicate column of bracket props → B-/I-/O sequence."""
        out, cur, inside = [], "O", False
        for tok in col:
            if tok == "*":
                out.append("I-" + cur if inside else "O")
            elif tok == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                out.append("B-" + cur)
                inside = ")" not in tok
            else:
                raise ValueError(f"unexpected props token {tok!r}")
        return out

    def _load(self):
        import gzip
        import tarfile

        self.sentences, self.predicates, self.label_seqs = [], [], []
        with tarfile.open(self.data_file) as tar:
            names = tar.getnames()

            def pick(suffix):
                cands = [n for n in names if n.endswith(suffix)]
                # the real conll05st-release archive carries BOTH
                # test.wsj and test.brown sections; the reference reads
                # test.wsj explicitly (conll05.py:175) — prefer it, and
                # never silently pair words/props from different sections
                wsj = [n for n in cands if "test.wsj" in n]
                chosen = wsj or cands
                if len(chosen) != 1:
                    raise ValueError(
                        f"{self.name}: expected one *{suffix} member "
                        f"(preferring test.wsj), found {cands}")
                return chosen[0]

            wname, pname = pick("words.gz"), pick("props.gz")
            if ("test.wsj" in wname) != ("test.wsj" in pname):
                raise ValueError(
                    f"{self.name}: words/props members come from "
                    f"different sections: {wname} vs {pname}")
            with gzip.GzipFile(fileobj=tar.extractfile(wname)) as wf, \
                    gzip.GzipFile(fileobj=tar.extractfile(pname)) as pf:
                words, prop_rows = [], []
                for wline, pline in zip(wf, pf):
                    w = wline.decode("utf-8", "ignore").strip()
                    cols = pline.decode("utf-8", "ignore").strip().split()
                    if not cols:  # blank line = sentence boundary
                        self._finish_sentence(words, prop_rows)
                        words, prop_rows = [], []
                        continue
                    words.append(w)
                    prop_rows.append(cols)
                self._finish_sentence(words, prop_rows)

    def _finish_sentence(self, words, prop_rows):
        if not words:
            return
        n_preds = len(prop_rows[0]) - 1
        verbs = [row[0] for row in prop_rows if row[0] != "-"]
        for k in range(n_preds):
            col = [row[1 + k] for row in prop_rows]
            labels = self._expand_props(col)
            self.sentences.append(list(words))
            self.predicates.append(verbs[k])
            self.label_seqs.append(labels)

    def __getitem__(self, idx):
        sent = self.sentences[idx]
        labels = self.label_seqs[idx]
        n = len(sent)
        v = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, key, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                              (0, "0", None), (1, "p1", "eos"),
                              (2, "p2", "eos")):
            j = v + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[key] = sent[j]
            else:
                ctx[key] = pad
        # reference conll05.py:40 UNK_IDX = 0 (NOT wmt14's 2): OOV words
        # must land on the same embedding row as reference-trained models
        wd = self.word_dict
        word_idx = [wd.get(w, 0) for w in sent]
        ctx_arr = {k: [wd.get(w, 0)] * n for k, w in ctx.items()}
        pred_idx = [self.predicate_dict.get(self.predicates[idx], 0)] * n
        label_idx = [self.label_dict[t] for t in labels]
        return (np.asarray(word_idx), np.asarray(ctx_arr["n2"]),
                np.asarray(ctx_arr["n1"]), np.asarray(ctx_arr["0"]),
                np.asarray(ctx_arr["p1"]), np.asarray(ctx_arr["p2"]),
                np.asarray(pred_idx), np.asarray(mark),
                np.asarray(label_idx))

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict


_ML_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class Movielens(_LocalFileDataset):
    """MovieLens ml-1m ratings (reference:
    python/paddle/text/datasets/movielens.py — zip with
    ``ml-1m/{movies,users,ratings}.dat`` of ``::``-separated records).
    Each sample is the reference's 8-array tuple: [uid], [is_female],
    [age_bucket], [job], [movie_id], category ids, title word ids,
    [rating*2-5]."""

    name = "movielens (ml-1m zip)"

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, **kwargs):
        self.test_ratio = float(test_ratio)
        self.rand_seed = int(rand_seed)
        super().__init__(data_file=data_file, mode=mode, **kwargs)

    def _load(self):
        import re
        import zipfile

        year_pat = re.compile(r"^(.*)\((\d+)\)$")
        movies: Dict[int, tuple] = {}
        users: Dict[int, list] = {}
        cat_set, title_words = set(), set()
        with zipfile.ZipFile(self.data_file) as z:
            root = next(n.split("/")[0] for n in z.namelist()
                        if n.endswith("movies.dat"))
            with z.open(f"{root}/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode(
                        "latin-1").strip().split("::")
                    cats = cats.split("|")
                    m = year_pat.match(title)
                    title = m.group(1).strip() if m else title
                    movies[int(mid)] = (cats, title)
                    cat_set.update(cats)
                    title_words.update(w.lower() for w in title.split())
            self.categories_dict = {c: i
                                    for i, c in enumerate(sorted(cat_set))}
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(sorted(title_words))}
            with z.open(f"{root}/users.dat") as f:
                for line in f:
                    uid, gender, age, job = line.decode(
                        "latin-1").strip().split("::")[:4]
                    users[int(uid)] = [
                        int(uid), 0 if gender == "M" else 1,
                        _ML_AGE_TABLE.index(int(age)), int(job)]
            rng = np.random.RandomState(self.rand_seed)
            is_test = self.mode == "test"
            self.samples = []
            with z.open(f"{root}/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < self.test_ratio) != is_test:
                        continue
                    uid, mid, rating = line.decode(
                        "latin-1").strip().split("::")[:3]
                    u = users[int(uid)]
                    cats, title = movies[int(mid)]
                    self.samples.append(tuple(np.asarray(a) for a in (
                        [u[0]], [u[1]], [u[2]], [u[3]], [int(mid)],
                        [self.categories_dict[c] for c in cats],
                        [self.movie_title_dict[w.lower()]
                         for w in title.split()],
                        [float(rating) * 2 - 5.0])))


# ---------------------------------------------------------------- tokenizer
class BasicTokenizer:
    """Whitespace + punctuation splitting with lowercasing/accent folding."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        import unicodedata

        if self.do_lower_case:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(c for c in text
                           if unicodedata.category(c) != "Mn")
        out = []
        word = []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif not ch.isalnum():
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out


class WordpieceTokenizer:
    """Greedy longest-match-first wordpiece (reference:
    faster_tokenizer_op.cc WordPieceTokenizer)."""

    def __init__(self, vocab: Dict[str, int], unk_token="[UNK]",
                 max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        tokens = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens


class BertTokenizer:
    def __init__(self, vocab_file=None, vocab: Optional[Dict[str, int]] = None,
                 do_lower_case=True, unk_token="[UNK]", cls_token="[CLS]",
                 sep_token="[SEP]", pad_token="[PAD]"):
        if vocab is None:
            if vocab_file is None:
                raise ValueError("pass vocab_file or vocab dict")
            vocab = {}
            with open(vocab_file, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    vocab[line.rstrip("\n")] = i
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab, unk_token)
        self.unk_token = unk_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.pad_token = pad_token

    def tokenize(self, text: str) -> List[str]:
        out = []
        for word in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(word))
        return out

    def convert_tokens_to_ids(self, tokens):
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def __call__(self, text, text_pair=None, max_length=None,
                 padding=False, truncation=False):
        tokens = [self.cls_token] + self.tokenize(text) + [self.sep_token]
        type_ids = [0] * len(tokens)
        if text_pair:
            pair = self.tokenize(text_pair) + [self.sep_token]
            tokens += pair
            type_ids += [1] * len(pair)
        ids = self.convert_tokens_to_ids(tokens)
        if truncation and max_length:
            ids = ids[:max_length]
            type_ids = type_ids[:max_length]
        attn = [1] * len(ids)
        if padding and max_length and len(ids) < max_length:
            pad_id = self.vocab.get(self.pad_token, 0)
            pad_n = max_length - len(ids)
            ids += [pad_id] * pad_n
            type_ids += [0] * pad_n
            attn += [0] * pad_n
        return {"input_ids": ids, "token_type_ids": type_ids,
                "attention_mask": attn}


# ---------------------------------------------------------------- viterbi
def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decode via lax.scan (reference: viterbi_decode op)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import Tensor, to_tensor

    pot = potentials if isinstance(potentials, Tensor) \
        else to_tensor(potentials)
    trans = transition_params if isinstance(transition_params, Tensor) \
        else to_tensor(transition_params)

    def _viterbi(p, tr):
        # p: [B, T, N]; tr: [N, N]
        def step(carry, emit):
            score = carry  # [B, N]
            cand = score[:, :, None] + tr[None]  # [B, N_from, N_to]
            best = jnp.max(cand, axis=1) + emit
            back = jnp.argmax(cand, axis=1)
            return best, back

        init = p[:, 0]
        score, backs = jax.lax.scan(step, init,
                                    jnp.moveaxis(p[:, 1:], 1, 0))
        last = jnp.argmax(score, axis=-1)

        def backtrack(carry, back):
            idx = carry
            prev = jnp.take_along_axis(back, idx[:, None], axis=1)[:, 0]
            return prev, prev

        _, path = jax.lax.scan(backtrack, last, backs, reverse=True)
        path = jnp.concatenate([jnp.moveaxis(path, 0, 1), last[:, None]], 1)
        return jnp.max(score, -1), path.astype(jnp.int64)

    return apply("viterbi_decode", _viterbi, pot, trans,
                 _differentiable=False)


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
