"""paddle.text (reference: python/paddle/text/ — NLP datasets) + a host-side
tokenizer (the reference's in-graph faster_tokenizer_op,
paddle/fluid/operators/string/faster_tokenizer_op.cc:525, becomes host
preprocessing feeding infeed on TPU)."""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "WMT16", "Conll05st",
           "Movielens",
           "BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "ViterbiDecoder", "viterbi_decode"]


class _LocalFileDataset(Dataset):
    name = "dataset"

    def __init__(self, data_file=None, mode="train", **kwargs):
        if data_file is None:
            raise ValueError(
                f"no network egress: pass data_file with a local copy of "
                f"{self.name}")
        self.data_file = data_file
        self.mode = mode
        self._load()

    def _load(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class UCIHousing(_LocalFileDataset):
    name = "uci_housing (housing.data)"

    def _load(self):
        raw = np.loadtxt(self.data_file)
        x = raw[:, :-1].astype(np.float32)
        y = raw[:, -1:].astype(np.float32)
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        n = int(len(x) * 0.8)
        if self.mode == "train":
            self.samples = list(zip(x[:n], y[:n]))
        else:
            self.samples = list(zip(x[n:], y[n:]))


class Imdb(_LocalFileDataset):
    name = "imdb (aclImdb tarball)"

    def _load(self):
        import re
        import tarfile

        pattern = re.compile(
            rf"aclImdb/{self.mode}/(pos|neg)/.*\.txt$")
        self.samples = []
        with tarfile.open(self.data_file) as tar:
            for member in tar.getmembers():
                m = pattern.match(member.name)
                if m:
                    text = tar.extractfile(member).read().decode(
                        "utf-8", "ignore")
                    label = 1 if m.group(1) == "pos" else 0
                    self.samples.append((text, np.asarray(label, np.int64)))


class Imikolov(_LocalFileDataset):
    """N-gram windows over the PTB-style imikolov corpus (reference:
    python/paddle/text/datasets/imikolov.py).  data_file: a text file of
    whitespace-tokenized sentences; yields (context..., target) id tuples
    over a min-frequency vocabulary like the reference."""

    name = "imikolov (simple-examples text file)"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, **kwargs):
        if str(data_type).upper() not in ("NGRAM", "SEQ"):
            raise ValueError(f"data_type must be NGRAM or SEQ, got "
                             f"{data_type!r}")
        self.data_type = data_type
        self.window_size = int(window_size)
        self.min_word_freq = int(min_word_freq)
        super().__init__(data_file=data_file, mode=mode, **kwargs)

    def _load(self):
        from collections import Counter

        lines = []
        with open(self.data_file, "r", encoding="utf-8",
                  errors="ignore") as f:
            for line in f:
                toks = line.strip().split()
                if toks:
                    lines.append(toks)
        freq = Counter(w for toks in lines for w in toks)
        vocab = {"<unk>": 0, "<s>": 1, "<e>": 2}
        for w, c in sorted(freq.items()):
            if c >= self.min_word_freq and w not in vocab:
                vocab[w] = len(vocab)
        self.word_idx = vocab
        unk = vocab["<unk>"]
        self.samples = []
        for toks in lines:
            ids = [vocab["<s>"]] + [vocab.get(w, unk) for w in toks]                 + [vocab["<e>"]]
            if self.data_type.upper() == "NGRAM":
                n = self.window_size
                for i in range(len(ids) - n + 1):
                    self.samples.append(tuple(
                        np.asarray(v, np.int64) for v in ids[i:i + n]))
            else:  # SEQ: (input, shifted-target) pairs
                self.samples.append(
                    (np.asarray(ids[:-1], np.int64),
                     np.asarray(ids[1:], np.int64)))


class WMT14(_LocalFileDataset):
    name = "wmt14"

    def _load(self):
        raise NotImplementedError("provide a local WMT14 archive")


class WMT16(WMT14):
    name = "wmt16"


class Conll05st(_LocalFileDataset):
    name = "conll05st"

    def _load(self):
        raise NotImplementedError("provide a local Conll05 archive")


class Movielens(_LocalFileDataset):
    name = "movielens"

    def _load(self):
        raise NotImplementedError("provide a local Movielens archive")


# ---------------------------------------------------------------- tokenizer
class BasicTokenizer:
    """Whitespace + punctuation splitting with lowercasing/accent folding."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        import unicodedata

        if self.do_lower_case:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(c for c in text
                           if unicodedata.category(c) != "Mn")
        out = []
        word = []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif not ch.isalnum():
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out


class WordpieceTokenizer:
    """Greedy longest-match-first wordpiece (reference:
    faster_tokenizer_op.cc WordPieceTokenizer)."""

    def __init__(self, vocab: Dict[str, int], unk_token="[UNK]",
                 max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        tokens = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens


class BertTokenizer:
    def __init__(self, vocab_file=None, vocab: Optional[Dict[str, int]] = None,
                 do_lower_case=True, unk_token="[UNK]", cls_token="[CLS]",
                 sep_token="[SEP]", pad_token="[PAD]"):
        if vocab is None:
            if vocab_file is None:
                raise ValueError("pass vocab_file or vocab dict")
            vocab = {}
            with open(vocab_file, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    vocab[line.rstrip("\n")] = i
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab, unk_token)
        self.unk_token = unk_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.pad_token = pad_token

    def tokenize(self, text: str) -> List[str]:
        out = []
        for word in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(word))
        return out

    def convert_tokens_to_ids(self, tokens):
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def __call__(self, text, text_pair=None, max_length=None,
                 padding=False, truncation=False):
        tokens = [self.cls_token] + self.tokenize(text) + [self.sep_token]
        type_ids = [0] * len(tokens)
        if text_pair:
            pair = self.tokenize(text_pair) + [self.sep_token]
            tokens += pair
            type_ids += [1] * len(pair)
        ids = self.convert_tokens_to_ids(tokens)
        if truncation and max_length:
            ids = ids[:max_length]
            type_ids = type_ids[:max_length]
        attn = [1] * len(ids)
        if padding and max_length and len(ids) < max_length:
            pad_id = self.vocab.get(self.pad_token, 0)
            pad_n = max_length - len(ids)
            ids += [pad_id] * pad_n
            type_ids += [0] * pad_n
            attn += [0] * pad_n
        return {"input_ids": ids, "token_type_ids": type_ids,
                "attention_mask": attn}


# ---------------------------------------------------------------- viterbi
def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decode via lax.scan (reference: viterbi_decode op)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import Tensor, to_tensor

    pot = potentials if isinstance(potentials, Tensor) \
        else to_tensor(potentials)
    trans = transition_params if isinstance(transition_params, Tensor) \
        else to_tensor(transition_params)

    def _viterbi(p, tr):
        # p: [B, T, N]; tr: [N, N]
        def step(carry, emit):
            score = carry  # [B, N]
            cand = score[:, :, None] + tr[None]  # [B, N_from, N_to]
            best = jnp.max(cand, axis=1) + emit
            back = jnp.argmax(cand, axis=1)
            return best, back

        init = p[:, 0]
        score, backs = jax.lax.scan(step, init,
                                    jnp.moveaxis(p[:, 1:], 1, 0))
        last = jnp.argmax(score, axis=-1)

        def backtrack(carry, back):
            idx = carry
            prev = jnp.take_along_axis(back, idx[:, None], axis=1)[:, 0]
            return prev, prev

        _, path = jax.lax.scan(backtrack, last, backs, reverse=True)
        path = jnp.concatenate([jnp.moveaxis(path, 0, 1), last[:, None]], 1)
        return jnp.max(score, -1), path.astype(jnp.int64)

    return apply("viterbi_decode", _viterbi, pot, trans,
                 _differentiable=False)


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
