"""paddle.incubate.autograd (reference:
python/paddle/incubate/autograd/__init__.py) — the functional autograd
API graduated to ``paddle.autograd`` in the reference too; incubate keeps
the original import path alive.  Same objects, one implementation."""
from ..autograd import Hessian, Jacobian, jvp, vjp  # noqa: F401

__all__ = ["vjp", "jvp", "Jacobian", "Hessian"]
