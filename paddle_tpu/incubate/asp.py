# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""2:4 structured sparsity (reference: python/paddle/fluid/contrib/sparsity —
ASP masks + OptimizerWithSparsityGuarantee).

TPU note: the MXU has no 2:4 sparse mode (that is A100 tensor-core
hardware); masks are still useful for model compression, so the masking
machinery is implemented and the speedup claim is explicitly not made.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

_masks = {}


def compute_mask_2_4(arr: np.ndarray) -> np.ndarray:
    """Keep the 2 largest |values| in every group of 4 along the last axis."""
    flat = arr.reshape(-1, arr.shape[-1])
    out = np.zeros_like(flat, dtype=bool)
    for r in range(flat.shape[0]):
        row = flat[r]
        n4 = (len(row) // 4) * 4
        groups = np.abs(row[:n4]).reshape(-1, 4)
        idx = np.argsort(-groups, axis=1)[:, :2]
        for g, (i, j) in enumerate(idx):
            out[r, g * 4 + i] = True
            out[r, g * 4 + j] = True
        out[r, n4:] = True
    return out.reshape(arr.shape)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    for name, p in model.named_parameters():
        if p.ndim < 2:
            continue
        mask = compute_mask_2_4(p.numpy())
        _masks[id(p)] = jnp.asarray(mask)
        p._value = p._value * _masks[id(p)].astype(p._value.dtype)
    return model


def decorate(optimizer):
    """Re-apply masks after each step (OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step():
        orig_step()
        for p, _, _ in optimizer._collect_params_grads():
            mask = _masks.get(id(p))
            if mask is not None:
                p._value = p._value * mask.astype(p._value.dtype)

    optimizer.step = step
    return optimizer


def check_sparsity(arr: np.ndarray, n=2, m=4) -> bool:
    flat = np.asarray(arr).reshape(-1)
    n4 = (len(flat) // m) * m
    groups = flat[:n4].reshape(-1, m)
    return bool(np.all((groups != 0).sum(axis=1) <= n))
