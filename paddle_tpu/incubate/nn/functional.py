"""Fused functional ops (reference: python/paddle/incubate/nn/functional/)."""
from __future__ import annotations

from ...nn.functional.attention import scaled_dot_product_attention


def fused_multi_head_attention(x, qkv_weight, linear_weight, *args, **kwargs):
    raise NotImplementedError(
        "use nn.MultiHeadAttention / F.scaled_dot_product_attention — the "
        "Pallas flash kernel is the fused path on TPU")


def fused_feedforward(x, linear1_weight, linear2_weight, *args, **kwargs):
    raise NotImplementedError(
        "XLA fuses the FFN chain automatically; use incubate.nn."
        "FusedFeedForward for the layer API")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ...nn.functional.common import linear

    if transpose_weight:
        from ...ops.manipulation import t as _t

        weight = _t(weight)
    return linear(x, weight, bias)


flash_attention = scaled_dot_product_attention
