"""Fused functional ops (reference: python/paddle/incubate/nn/functional/)."""
from __future__ import annotations

from ...nn.functional.attention import scaled_dot_product_attention


def _fused_dropout(v, key, p, mode):
    """Shared dropout for the fused blocks (reference fused ops' dropout
    semantics): upscale_in_train scales kept values by 1/(1-p)."""
    import jax
    import jax.numpy as jnp

    keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
    scale = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0
    return jnp.where(keep, v * scale, 0.0).astype(v.dtype)


def _fused_infer_scale(v, p, mode, training):
    """downscale_in_infer: no train-time upscale, so eval multiplies by
    the keep probability."""
    if mode == "downscale_in_infer" and not training and p:
        return (v * (1.0 - p)).astype(v.dtype)
    return v


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """Whole attention block from explicit weights (reference:
    incubate/nn/functional/fused_transformer.py fused_multi_head_attention
    over fused_attention_op.cu).  qkv_weight: [3, n_heads, head_dim, D];
    linear_weight: [D, D].  On TPU the fusion is XLA's + the flash kernel
    inside scaled_dot_product_attention."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply
    from ...core.tensor import Tensor, to_tensor
    from ...ops import random as rnd

    # reference fused_attention_op.cu applies dropout after softmax
    # (attn_dropout_rate) and after the out-linear (dropout_rate); draw
    # framework-RNG keys outside the pure fn (ADVICE r2: rates were
    # silently ignored)
    keys = {}
    if training and attn_dropout_rate:
        keys["attn"] = rnd.next_key()
    if training and dropout_rate:
        keys["out"] = rnd.next_key()

    def _drop(v, key, p):
        return _fused_dropout(v, key, p, mode)

    def _infer_scale(v, p):
        return _fused_infer_scale(v, p, mode, training)

    def _v(t):
        return t._value if isinstance(t, Tensor) else jnp.asarray(t)

    def _ln(v, scale, bias, eps):
        mu = jnp.mean(v, -1, keepdims=True)
        var = jnp.var(v, -1, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            out = out * _v(scale)
        if bias is not None:
            out = out + _v(bias)
        return out

    def _fn(xv, qkv_w, lin_w, *rest):
        names = []
        extras = {}
        ri = 0
        for nm, t in [("pre_s", pre_ln_scale), ("pre_b", pre_ln_bias),
                      ("ln_s", ln_scale), ("ln_b", ln_bias),
                      ("qkv_b", qkv_bias), ("lin_b", linear_bias),
                      ("mask", attn_mask)]:
            if t is not None:
                extras[nm] = rest[ri]
                ri += 1
        residual = xv
        h = xv
        if pre_layer_norm:
            h = _ln(h, extras.get("pre_s"), extras.get("pre_b"),
                    pre_ln_epsilon)
        three, nh, hd, D = qkv_w.shape
        B, T, _ = h.shape
        qkv = jnp.einsum("btd,khed->btkhe", h.astype(jnp.float32),
                         qkv_w.astype(jnp.float32))
        if "qkv_b" in extras:
            qkv = qkv + extras["qkv_b"].reshape(1, 1, 3, nh, hd)
        q, k, v = (qkv[:, :, 0].astype(xv.dtype),
                   qkv[:, :, 1].astype(xv.dtype),
                   qkv[:, :, 2].astype(xv.dtype))
        scores = jnp.einsum("bthe,bshe->bhts", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        if "mask" in extras:
            scores = scores + extras["mask"].astype(jnp.float32)
        probs = jax.nn.softmax(scores, -1).astype(xv.dtype)
        if "attn" in keys:
            probs = _drop(probs, keys["attn"], attn_dropout_rate)
        probs = _infer_scale(probs, attn_dropout_rate)
        ctx = jnp.einsum("bhts,bshe->bthe", probs, v).reshape(B, T, nh * hd)
        out = ctx @ lin_w.astype(ctx.dtype)
        if "lin_b" in extras:
            out = out + extras["lin_b"]
        if "out" in keys:
            out = _drop(out, keys["out"], dropout_rate)
        out = _infer_scale(out, dropout_rate)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, extras.get("ln_s"), extras.get("ln_b"),
                      ln_epsilon)
        return out.astype(xv.dtype)

    args = [x if isinstance(x, Tensor) else to_tensor(x),
            qkv_weight if isinstance(qkv_weight, Tensor)
            else to_tensor(qkv_weight),
            linear_weight if isinstance(linear_weight, Tensor)
            else to_tensor(linear_weight)]
    for t in (pre_ln_scale, pre_ln_bias, ln_scale, ln_bias, qkv_bias,
              linear_bias, attn_mask):
        if t is not None:
            args.append(t if isinstance(t, Tensor) else to_tensor(t))
    return apply("fused_multi_head_attention", _fn, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=None,
                      ring_id=-1, add_residual=True, name=None):
    """Whole FFN block from explicit weights (reference:
    fused_feedforward over fused_feedforward_op.cu): optional pre/post
    layernorm, two linears, activation, residual.  XLA fuses the chain."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply
    from ...core.tensor import Tensor, to_tensor
    from ...ops import random as rnd

    acts = {"relu": jax.nn.relu,
            "gelu": lambda v: jax.nn.gelu(v, approximate=False)}
    act = acts[activation]

    # reference fused_feedforward_op.cu: dropout1 after the activation,
    # dropout2 after linear2 (before the residual add)
    drop_mode = mode or "upscale_in_train"
    keys = {}
    if training and dropout1_rate:
        keys["d1"] = rnd.next_key()
    if training and dropout2_rate:
        keys["d2"] = rnd.next_key()

    def _drop(v, key, p):
        return _fused_dropout(v, key, p, drop_mode)

    def _infer_scale(v, p):
        return _fused_infer_scale(v, p, drop_mode, training)

    def _v(t):
        return t._value if isinstance(t, Tensor) else jnp.asarray(t)

    def _ln(v, scale, bias, eps):
        mu = jnp.mean(v, -1, keepdims=True)
        var = jnp.var(v, -1, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            out = out * _v(scale)
        if bias is not None:
            out = out + _v(bias)
        return out

    def _fn(xv, w1, w2, *rest):
        extras = {}
        ri = 0
        for nm, t in [("b1", linear1_bias), ("b2", linear2_bias),
                      ("s1", ln1_scale), ("sb1", ln1_bias),
                      ("s2", ln2_scale), ("sb2", ln2_bias)]:
            if t is not None:
                extras[nm] = rest[ri]
                ri += 1
        residual = xv
        h = xv
        if pre_layer_norm:
            h = _ln(h, extras.get("s1"), extras.get("sb1"), ln1_epsilon)
        h = h @ w1.astype(h.dtype)
        if "b1" in extras:
            h = h + extras["b1"]
        h = act(h)
        if "d1" in keys:
            h = _drop(h, keys["d1"], dropout1_rate)
        h = _infer_scale(h, dropout1_rate)
        h = h @ w2.astype(h.dtype)
        if "b2" in extras:
            h = h + extras["b2"]
        if "d2" in keys:
            h = _drop(h, keys["d2"], dropout2_rate)
        h = _infer_scale(h, dropout2_rate)
        if add_residual:
            h = residual + h
        if not pre_layer_norm:
            h = _ln(h, extras.get("s2"), extras.get("sb2"), ln2_epsilon)
        return h.astype(xv.dtype)

    args = [x if isinstance(x, Tensor) else to_tensor(x),
            linear1_weight if isinstance(linear1_weight, Tensor)
            else to_tensor(linear1_weight),
            linear2_weight if isinstance(linear2_weight, Tensor)
            else to_tensor(linear2_weight)]
    for t in (linear1_bias, linear2_bias, ln1_scale, ln1_bias, ln2_scale,
              ln2_bias):
        if t is not None:
            args.append(t if isinstance(t, Tensor) else to_tensor(t))
    return apply("fused_feedforward", _fn, *args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ...nn.functional.common import linear

    if transpose_weight:
        from ...ops.manipulation import t as _t

        weight = _t(weight)
    return linear(x, weight, bias)


flash_attention = scaled_dot_product_attention
