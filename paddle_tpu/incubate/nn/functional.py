# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Fused functional ops (reference: python/paddle/incubate/nn/functional/)."""
from __future__ import annotations

from ...nn.functional.attention import scaled_dot_product_attention


def _fused_dropout(v, key, p, mode):
    """Shared dropout for the fused blocks (reference fused ops' dropout
    semantics): upscale_in_train scales kept values by 1/(1-p)."""
    import jax
    import jax.numpy as jnp

    keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
    scale = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0
    return jnp.where(keep, v * scale, 0.0).astype(v.dtype)


def _fused_infer_scale(v, p, mode, training):
    """downscale_in_infer: no train-time upscale, so eval multiplies by
    the keep probability."""
    if mode == "downscale_in_infer" and not training and p:
        return (v * (1.0 - p)).astype(v.dtype)
    return v


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """Whole attention block from explicit weights (reference:
    incubate/nn/functional/fused_transformer.py fused_multi_head_attention
    over fused_attention_op.cu).  qkv_weight: [3, n_heads, head_dim, D];
    linear_weight: [D, D].  On TPU the fusion is XLA's + the flash kernel
    inside scaled_dot_product_attention."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply
    from ...core.tensor import Tensor, to_tensor
    from ...ops import random as rnd

    # reference fused_attention_op.cu applies dropout after softmax
    # (attn_dropout_rate) and after the out-linear (dropout_rate); draw
    # framework-RNG keys outside the pure fn (ADVICE r2: rates were
    # silently ignored)
    keys = {}
    if training and attn_dropout_rate:
        keys["attn"] = rnd.next_key()
    if training and dropout_rate:
        keys["out"] = rnd.next_key()

    def _drop(v, key, p):
        return _fused_dropout(v, key, p, mode)

    def _infer_scale(v, p):
        return _fused_infer_scale(v, p, mode, training)

    def _v(t):
        return t._value if isinstance(t, Tensor) else jnp.asarray(t)

    def _ln(v, scale, bias, eps):
        mu = jnp.mean(v, -1, keepdims=True)
        var = jnp.var(v, -1, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            out = out * _v(scale)
        if bias is not None:
            out = out + _v(bias)
        return out

    def _fn(xv, qkv_w, lin_w, *rest):
        names = []
        extras = {}
        ri = 0
        for nm, t in [("pre_s", pre_ln_scale), ("pre_b", pre_ln_bias),
                      ("ln_s", ln_scale), ("ln_b", ln_bias),
                      ("qkv_b", qkv_bias), ("lin_b", linear_bias),
                      ("mask", attn_mask)]:
            if t is not None:
                extras[nm] = rest[ri]
                ri += 1
        residual = xv
        h = xv
        if pre_layer_norm:
            h = _ln(h, extras.get("pre_s"), extras.get("pre_b"),
                    pre_ln_epsilon)
        three, nh, hd, D = qkv_w.shape
        B, T, _ = h.shape
        qkv = jnp.einsum("btd,khed->btkhe", h.astype(jnp.float32),
                         qkv_w.astype(jnp.float32))
        if "qkv_b" in extras:
            qkv = qkv + extras["qkv_b"].reshape(1, 1, 3, nh, hd)
        q, k, v = (qkv[:, :, 0].astype(xv.dtype),
                   qkv[:, :, 1].astype(xv.dtype),
                   qkv[:, :, 2].astype(xv.dtype))
        scores = jnp.einsum("bthe,bshe->bhts", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        if "mask" in extras:
            scores = scores + extras["mask"].astype(jnp.float32)
        probs = jax.nn.softmax(scores, -1).astype(xv.dtype)
        if "attn" in keys:
            probs = _drop(probs, keys["attn"], attn_dropout_rate)
        probs = _infer_scale(probs, attn_dropout_rate)
        ctx = jnp.einsum("bhts,bshe->bthe", probs, v).reshape(B, T, nh * hd)
        out = ctx @ lin_w.astype(ctx.dtype)
        if "lin_b" in extras:
            out = out + extras["lin_b"]
        if "out" in keys:
            out = _drop(out, keys["out"], dropout_rate)
        out = _infer_scale(out, dropout_rate)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, extras.get("ln_s"), extras.get("ln_b"),
                      ln_epsilon)
        return out.astype(xv.dtype)

    args = [x if isinstance(x, Tensor) else to_tensor(x),
            qkv_weight if isinstance(qkv_weight, Tensor)
            else to_tensor(qkv_weight),
            linear_weight if isinstance(linear_weight, Tensor)
            else to_tensor(linear_weight)]
    for t in (pre_ln_scale, pre_ln_bias, ln_scale, ln_bias, qkv_bias,
              linear_bias, attn_mask):
        if t is not None:
            args.append(t if isinstance(t, Tensor) else to_tensor(t))
    return apply("fused_multi_head_attention", _fn, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=None,
                      ring_id=-1, add_residual=True, name=None):
    """Whole FFN block from explicit weights (reference:
    fused_feedforward over fused_feedforward_op.cu): optional pre/post
    layernorm, two linears, activation, residual.  XLA fuses the chain."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply
    from ...core.tensor import Tensor, to_tensor
    from ...ops import random as rnd

    acts = {"relu": jax.nn.relu,
            "gelu": lambda v: jax.nn.gelu(v, approximate=False)}
    act = acts[activation]

    # reference fused_feedforward_op.cu: dropout1 after the activation,
    # dropout2 after linear2 (before the residual add)
    drop_mode = mode or "upscale_in_train"
    keys = {}
    if training and dropout1_rate:
        keys["d1"] = rnd.next_key()
    if training and dropout2_rate:
        keys["d2"] = rnd.next_key()

    def _drop(v, key, p):
        return _fused_dropout(v, key, p, drop_mode)

    def _infer_scale(v, p):
        return _fused_infer_scale(v, p, drop_mode, training)

    def _v(t):
        return t._value if isinstance(t, Tensor) else jnp.asarray(t)

    def _ln(v, scale, bias, eps):
        mu = jnp.mean(v, -1, keepdims=True)
        var = jnp.var(v, -1, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            out = out * _v(scale)
        if bias is not None:
            out = out + _v(bias)
        return out

    def _fn(xv, w1, w2, *rest):
        extras = {}
        ri = 0
        for nm, t in [("b1", linear1_bias), ("b2", linear2_bias),
                      ("s1", ln1_scale), ("sb1", ln1_bias),
                      ("s2", ln2_scale), ("sb2", ln2_bias)]:
            if t is not None:
                extras[nm] = rest[ri]
                ri += 1
        residual = xv
        h = xv
        if pre_layer_norm:
            h = _ln(h, extras.get("s1"), extras.get("sb1"), ln1_epsilon)
        h = h @ w1.astype(h.dtype)
        if "b1" in extras:
            h = h + extras["b1"]
        h = act(h)
        if "d1" in keys:
            h = _drop(h, keys["d1"], dropout1_rate)
        h = _infer_scale(h, dropout1_rate)
        h = h @ w2.astype(h.dtype)
        if "b2" in extras:
            h = h + extras["b2"]
        if "d2" in keys:
            h = _drop(h, keys["d2"], dropout2_rate)
        h = _infer_scale(h, dropout2_rate)
        if add_residual:
            h = residual + h
        if not pre_layer_norm:
            h = _ln(h, extras.get("s2"), extras.get("sb2"), ln2_epsilon)
        return h.astype(xv.dtype)

    args = [x if isinstance(x, Tensor) else to_tensor(x),
            linear1_weight if isinstance(linear1_weight, Tensor)
            else to_tensor(linear1_weight),
            linear2_weight if isinstance(linear2_weight, Tensor)
            else to_tensor(linear2_weight)]
    for t in (linear1_bias, linear2_bias, ln1_scale, ln1_bias, ln2_scale,
              ln2_bias):
        if t is not None:
            args.append(t if isinstance(t, Tensor) else to_tensor(t))
    return apply("fused_feedforward", _fn, *args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ...nn.functional.common import linear

    if transpose_weight:
        from ...ops.manipulation import t as _t

        weight = _t(weight)
    return linear(x, weight, bias)


flash_attention = scaled_dot_product_attention


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """Whole decoder stack in one op (reference:
    incubate/nn/functional/fused_transformer.py fused_multi_transformer
    over fused_multi_transformer_op.cu — per layer: LN, fused-QKV
    attention, out-proj + residual, LN, FFN, residual; with a static
    [2, B, H, max_seq, head_dim] KV cache per layer and `time_step`
    selecting decode mode).

    TPU-native: per-layer math is pure jnp under one traced op — XLA fuses
    LN/bias/residual chains into the matmuls, and the decode path updates
    the cache with lax.dynamic_update_slice (static shapes, jit-stable).
    qkv layout follows the reference: [3, n_heads, head_dim, D] when
    trans_qkvw (y = x @ W^T per fused head)."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply
    from ...core.tensor import Tensor
    from ...ops import random as rnd

    n_layers = len(qkv_weights)
    decode = cache_kvs is not None and time_step is not None
    ts = None
    if decode:
        ts = int(time_step.numpy() if hasattr(time_step, "numpy")
                 else time_step)
    keys = [rnd.next_key() if (training and dropout_rate) else None
            for _ in range(2 * n_layers)]

    def _ln(v, s, b):
        mu = jnp.mean(v, -1, keepdims=True)
        var = jnp.var(v, -1, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + epsilon)
        if s is not None:
            out = out * s
        if b is not None:
            out = out + b
        return out

    def _drop(v, k):
        if k is None or not dropout_rate:
            return _fused_infer_scale(v, dropout_rate, mode, training)
        return _fused_dropout(v, k, dropout_rate, mode)

    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]

    def _opt(lst):
        return lst if lst is not None else [None] * n_layers

    # one presence plan shared by packer AND consumer — per-layer, per
    # slot, from the ACTUAL values (a per-element None in e.g.
    # qkv_biases=[b0, None] must skip in both places identically)
    groups_per_layer = [
        _opt(ln_scales), _opt(ln_biases), list(qkv_weights),
        _opt(qkv_biases), list(linear_weights), _opt(linear_biases),
        _opt(ffn_ln_scales), _opt(ffn_ln_biases), list(ffn1_weights),
        _opt(ffn1_biases), list(ffn2_weights), _opt(ffn2_biases),
        _opt(cache_kvs),
        [attn_mask] * n_layers if attn_mask is not None
        else [None] * n_layers]
    present = [[g[li] is not None for g in groups_per_layer]
               for li in range(n_layers)]

    def _fn(xv, *flat):
        it = iter(flat)

        def nxt(has):
            return next(it) if has else None

        outs_caches = []
        h = xv
        B, S, D = h.shape
        for li in range(n_layers):
            (lns, lnb, qkvw, qkvb, ow, ob, flns, flnb, w1, b1, w2, b2,
             cache, mask) = [nxt(p) for p in present[li]]
            if qkvw is None or ow is None or w1 is None or w2 is None:
                raise ValueError(
                    f"layer {li}: qkv/linear/ffn weights are required")

            residual = h
            z = _ln(h, lns, lnb) if pre_layer_norm else h
            if trans_qkvw:  # [3, H, hd, D] -> project via x @ W^T
                n_heads, head_dim = qkvw.shape[1], qkvw.shape[2]
                qkv = jnp.einsum("bsd,thed->bsthe", z, qkvw)
            else:           # [3, D, H, hd]
                n_heads, head_dim = qkvw.shape[2], qkvw.shape[3]
                qkv = jnp.einsum("bsd,tdhe->bsthe", z, qkvw)
            if qkvb is not None:
                qkv = qkv + qkvb[None, None]
            q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
            q = jnp.moveaxis(q, 1, 2)  # [B, H, S, hd]
            k = jnp.moveaxis(k, 1, 2)
            v = jnp.moveaxis(v, 1, 2)
            new_cache = None
            if cache is not None:
                if ts is not None:       # decode: one step at position ts
                    cache = jax.lax.dynamic_update_slice(
                        cache, jnp.stack([k, v])[:, :, :, :1],
                        (0, 0, 0, ts, 0))
                    k_all = cache[0]
                    v_all = cache[1]
                    Tmax = k_all.shape[2]
                    pos_ok = jnp.arange(Tmax)[None, None, None, :] <= ts
                    scores = jnp.einsum("bhqe,bhke->bhqk", q, k_all) \
                        / jnp.sqrt(float(head_dim))
                    scores = jnp.where(pos_ok, scores, -1e30)
                    new_cache = cache
                else:                    # prefill: write [0, S)
                    cache = jax.lax.dynamic_update_slice(
                        cache, jnp.stack([k, v]), (0, 0, 0, 0, 0))
                    new_cache = cache
                    k_all, v_all = k, v
                    scores = jnp.einsum("bhqe,bhke->bhqk", q, k) \
                        / jnp.sqrt(float(head_dim))
            else:
                k_all, v_all = k, v
                scores = jnp.einsum("bhqe,bhke->bhqk", q, k) \
                    / jnp.sqrt(float(head_dim))
            # reference fused_multi_transformer_op.cu adds ONLY the
            # caller's src_mask — causality is the caller's mask to build
            # (forcing tril here would corrupt prefix-LM / encoder-style
            # bidirectional prefills).  The decode-path pos_ok bound above
            # is different: it hides UNWRITTEN cache slots, not attention
            # structure.
            if mask is not None:
                scores = scores + mask
            attn = jax.nn.softmax(scores, -1)
            ctx = jnp.einsum("bhqk,bhke->bhqe", attn, v_all)
            ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, S, n_heads * head_dim)
            out = ctx @ ow
            if ob is not None:
                out = out + ob
            h = residual + _drop(out, keys[2 * li])
            if not pre_layer_norm:
                h = _ln(h, lns, lnb)
            residual = h
            z = _ln(h, flns, flnb) if pre_layer_norm else h
            f = act(z @ w1 + (b1 if b1 is not None else 0.0))
            f = f @ w2
            if b2 is not None:
                f = f + b2
            h = residual + _drop(f, keys[2 * li + 1])
            if not pre_layer_norm:
                h = _ln(h, flns, flnb)
            if new_cache is not None:
                outs_caches.append(new_cache)
        if outs_caches:
            return tuple([h] + outs_caches)
        return h

    flat_args = []
    for li in range(n_layers):
        for g in groups_per_layer:
            if g[li] is not None:
                flat_args.append(g[li])
    res = apply("fused_multi_transformer", _fn, x, *flat_args)
    if cache_kvs is not None:
        if isinstance(res, (list, tuple)):
            out, new_caches = res[0], list(res[1:])
        else:
            out, new_caches = res, []
        for dst, src in zip(cache_kvs, new_caches):
            if isinstance(dst, Tensor):
                dst._value = src._value if isinstance(src, Tensor) else src
        return out, cache_kvs
    return res
