"""paddle.incubate.nn: fused transformer layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py backed by
fused_attention_op.cu / fused_feedforward_op.cu).

On TPU the fusion comes from the Pallas flash-attention kernel + XLA
elementwise fusion, so these are thin compositions with the reference API.
"""
from __future__ import annotations

from ... import nn
from ...nn import functional as F
from . import functional  # noqa: F401


class FusedMultiHeadAttention(nn.Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, normalize_before=False, kdim=None,
                 vdim=None, need_weights=False, qkv_weight_attr=None, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.norm = nn.LayerNorm(embed_dim)
        self.attn = nn.MultiHeadAttention(embed_dim, num_heads,
                                          attn_dropout_rate, kdim, vdim)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        if self.normalize_before:
            query = self.norm(query)
        out = self.attn(query, key, value, attn_mask)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", act_dropout_rate=None,
                 normalize_before=False, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.norm = nn.LayerNorm(d_model)
        self.fc1 = nn.Linear(d_model, dim_feedforward)
        self.fc2 = nn.Linear(dim_feedforward, d_model)
        self.dropout = nn.Dropout(act_dropout_rate or dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.fc2(self.dropout(self.activation(self.fc1(src))))
        out = residual + self.dropout2(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, **kwargs):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate, attn_dropout_rate or dropout_rate,
            normalize_before)
        self.ffn = FusedFeedForward(d_model, dim_feedforward, dropout_rate,
                                    activation, act_dropout_rate,
                                    normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedLinear(nn.Linear):
    pass
