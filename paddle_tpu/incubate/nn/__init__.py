"""paddle.incubate.nn: fused transformer layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py backed by
fused_attention_op.cu / fused_feedforward_op.cu).

On TPU the fusion comes from the Pallas flash-attention kernel + XLA
elementwise fusion, so these are thin compositions with the reference API.
"""
from __future__ import annotations

from ... import nn
from ...nn import functional as F
from . import functional  # noqa: F401


class FusedMultiHeadAttention(nn.Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, normalize_before=False, kdim=None,
                 vdim=None, need_weights=False, qkv_weight_attr=None, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.norm = nn.LayerNorm(embed_dim)
        self.attn = nn.MultiHeadAttention(embed_dim, num_heads,
                                          attn_dropout_rate, kdim, vdim)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        if self.normalize_before:
            query = self.norm(query)
        out = self.attn(query, key, value, attn_mask)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", act_dropout_rate=None,
                 normalize_before=False, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.norm = nn.LayerNorm(d_model)
        self.fc1 = nn.Linear(d_model, dim_feedforward)
        self.fc2 = nn.Linear(dim_feedforward, d_model)
        self.dropout = nn.Dropout(act_dropout_rate or dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.fc2(self.dropout(self.activation(self.fc1(src))))
        out = residual + self.dropout2(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, **kwargs):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate, attn_dropout_rate or dropout_rate,
            normalize_before)
        self.ffn = FusedFeedForward(d_model, dim_feedforward, dropout_rate,
                                    activation, act_dropout_rate,
                                    normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedLinear(nn.Linear):
    pass


class FusedMultiTransformer(nn.Layer):
    """Stacked fused decoder (reference:
    incubate/nn/layer/fused_transformer.py FusedMultiTransformer — the
    serving-path transformer used by PaddleNLP's generation engine, with
    per-layer weight lists and a static KV cache).  Forward delegates to
    functional.fused_multi_transformer; `caches` + `time_step` select
    prefill vs decode exactly as the reference op does."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"num_heads ({num_heads}) must divide embed_dim "
                f"({embed_dim})")
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.embed_dim = embed_dim
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        self.trans_qkvw = trans_qkvw
        self.num_layers = num_layers
        from ...nn import initializer as I

        def mk(shape, attrs, i, is_bias=False, one=False):
            attr = attrs[i] if isinstance(attrs, (list, tuple)) else attrs
            init = None
            if attr is not None and hasattr(attr, "initializer"):
                init = attr.initializer
            if init is None:
                init = I.Constant(1.0) if one else (
                    I.Constant(0.0) if is_bias else I.XavierUniform())
            return self.create_parameter(
                list(shape), default_initializer=init, is_bias=is_bias)

        H, hd, D, dff = num_heads, self.head_dim, embed_dim, dim_feedforward
        # ParameterList, NOT plain lists: Layer.__setattr__ only registers
        # Parameter/Layer values, so a bare list would leave every weight
        # out of parameters()/state_dict() — optimizers and checkpoints
        # would silently see an empty model
        self.ln_scales, self.ln_biases = nn.ParameterList(), nn.ParameterList()
        self.qkv_weights = nn.ParameterList()
        self.qkv_biases = nn.ParameterList()
        self.linear_weights = nn.ParameterList()
        self.linear_biases = nn.ParameterList()
        self.ffn_ln_scales = nn.ParameterList()
        self.ffn_ln_biases = nn.ParameterList()
        self.ffn1_weights = nn.ParameterList()
        self.ffn1_biases = nn.ParameterList()
        self.ffn2_weights = nn.ParameterList()
        self.ffn2_biases = nn.ParameterList()
        for i in range(num_layers):
            self.ln_scales.append(mk([D], ln_scale_attrs, i, one=True))
            self.ln_biases.append(mk([D], ln_bias_attrs, i, is_bias=True))
            qkv_shape = [3, H, hd, D] if trans_qkvw else [3, D, H, hd]
            self.qkv_weights.append(mk(qkv_shape, qkv_weight_attrs, i))
            self.qkv_biases.append(mk([3, H, hd], qkv_bias_attrs, i,
                                      is_bias=True))
            self.linear_weights.append(mk([D, D], linear_weight_attrs, i))
            self.linear_biases.append(mk([D], linear_bias_attrs, i,
                                         is_bias=True))
            self.ffn_ln_scales.append(mk([D], ffn_ln_scale_attrs, i,
                                         one=True))
            self.ffn_ln_biases.append(mk([D], ffn_ln_bias_attrs, i,
                                         is_bias=True))
            self.ffn1_weights.append(mk([D, dff], ffn1_weight_attrs, i))
            self.ffn1_biases.append(mk([dff], ffn1_bias_attrs, i,
                                       is_bias=True))
            self.ffn2_weights.append(mk([dff, D], ffn2_weight_attrs, i))
            self.ffn2_biases.append(mk([D], ffn2_bias_attrs, i,
                                       is_bias=True))

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        out = functional.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self.epsilon,
            cache_kvs=caches, time_step=time_step, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate, activation=self.activation,
            training=self.training, trans_qkvw=self.trans_qkvw)
        return out
