"""paddle.incubate.optimizer (reference:
python/paddle/incubate/optimizer/__init__.py)."""
from . import functional  # noqa: F401
