# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""Functional quasi-Newton minimizers (reference:
python/paddle/incubate/optimizer/functional/{bfgs,lbfgs}.py —
minimize_bfgs returns (is_converge, num_func_calls, position, value,
gradient, inverse_hessian); minimize_lbfgs drops the Hessian).

Design: the reference builds these as static-graph while_loops so the
whole solve lives in one program.  Here the solve runs eagerly over
device arrays — each iteration is two fused XLA calls (value_and_grad +
the rank-2 update) — and the strong-Wolfe line search is the standard
bracket/zoom of Nocedal & Wright Alg. 3.5/3.6, the same scheme the
reference's line_search.py implements.  Positive-definiteness is
safeguarded by skipping the quasi-Newton update when s·y <= eps (the
curvature condition fails only when the line search bailed early)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _prep(objective_func, initial_position, dtype):
    jdt = jnp.dtype(dtype)
    x0 = jnp.asarray(
        initial_position._value if isinstance(initial_position, Tensor)
        else np.asarray(initial_position), jdt).reshape(-1)

    calls = [0]

    def f_g(x):
        calls[0] += 1
        val, grad = _vg(x)
        return val, grad

    def scalar_fn(x):
        out = objective_func(Tensor(x))
        v = out._value if isinstance(out, Tensor) else jnp.asarray(out)
        return v.reshape(())

    _vg = jax.jit(jax.value_and_grad(scalar_fn))
    return x0, f_g, calls


def _strong_wolfe(f_g, xk, pk, f0, df0, alpha0, max_iters, c1=1e-4, c2=0.9):
    """Bracket/zoom strong-Wolfe search along pk.  Returns
    (alpha, f_new, g_new, ok)."""

    def phi(a):
        return f_g(xk + a * pk)

    def dphi(g):
        return float(jnp.dot(g, pk))

    a_prev, f_prev, g_prev = 0.0, f0, None
    d0 = df0
    a = float(alpha0)
    f_lo, a_lo, g_lo = f0, 0.0, None
    a_hi = f_hi = None
    for i in range(max_iters):
        f_a, g_a = phi(a)
        if (f_a > f0 + c1 * a * d0) or (i > 0 and f_a >= f_prev):
            a_lo, f_lo, a_hi, f_hi = a_prev, f_prev, a, f_a
            g_lo = g_prev
            break
        d_a = dphi(g_a)
        if abs(d_a) <= -c2 * d0:
            return a, f_a, g_a, True
        if d_a >= 0:
            a_lo, f_lo, a_hi, f_hi = a, f_a, a_prev, f_prev
            g_lo = g_a
            break
        a_prev, f_prev, g_prev = a, f_a, g_a
        a *= 2.0
    else:
        return a_prev, f_prev, g_prev, False

    # zoom (Alg. 3.6): bisection flavor — robust, no cubic bookkeeping
    for _ in range(max_iters):
        a_j = 0.5 * (a_lo + a_hi)
        f_j, g_j = phi(a_j)
        if (f_j > f0 + c1 * a_j * d0) or (f_j >= f_lo):
            a_hi, f_hi = a_j, f_j
        else:
            d_j = dphi(g_j)
            if abs(d_j) <= -c2 * d0:
                return a_j, f_j, g_j, True
            if d_j * (a_hi - a_lo) >= 0:
                a_hi, f_hi = a_lo, f_lo
            a_lo, f_lo, g_lo = a_j, f_j, g_j
        if abs(a_hi - a_lo) < 1e-12:
            break
    if g_lo is None:
        f_lo, g_lo = phi(a_lo)
    return a_lo, f_lo, g_lo, False


def _pack(is_converge, calls, x, f, g, H=None):
    out = [Tensor(jnp.asarray(is_converge)),
           Tensor(jnp.asarray(calls, jnp.int32)),
           Tensor(x), Tensor(f), Tensor(g)]
    if H is not None:
        out.append(Tensor(H))
    return tuple(out)


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            f"only strong_wolfe line search is supported, got "
            f"{line_search_fn!r}")
    x, f_g, calls = _prep(objective_func, initial_position, dtype)
    n = x.shape[0]
    identity = jnp.eye(n, dtype=x.dtype)
    H = identity
    if initial_inverse_hessian_estimate is not None:
        H0 = initial_inverse_hessian_estimate
        H = jnp.asarray(H0._value if isinstance(H0, Tensor)
                        else np.asarray(H0), x.dtype)
        if not bool(jnp.allclose(H, H.T, atol=1e-6)):
            raise ValueError(
                "initial_inverse_hessian_estimate must be symmetric")
    f, g = f_g(x)
    is_converge = False
    for _ in range(int(max_iters)):
        gnorm = float(jnp.max(jnp.abs(g)))
        if gnorm < tolerance_grad:
            is_converge = True
            break
        p = -(H @ g)
        d0 = float(jnp.dot(g, p))
        if d0 >= 0:  # H lost positive-definiteness: restart on identity
            H = identity
            p = -g
            d0 = float(jnp.dot(g, p))
        alpha, f_new, g_new, _ok = _strong_wolfe(
            f_g, x, p, float(f), d0, initial_step_length,
            int(max_line_search_iters))
        s = alpha * p
        if float(jnp.max(jnp.abs(s))) < tolerance_change:
            is_converge = True
            x, f, g = x + s, f_new, g_new
            break
        x_new = x + s
        y = g_new - g
        sy = float(jnp.dot(s, y))
        if sy > 1e-10:  # curvature ok -> rank-2 BFGS update (N&W 6.17)
            rho = 1.0 / sy
            V = identity - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        x, f, g = x_new, f_new, g_new
    return _pack(is_converge, calls[0], x, f, g, H)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8,
                   tolerance_change=1e-8,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            f"only strong_wolfe line search is supported, got "
            f"{line_search_fn!r}")
    x, f_g, calls = _prep(objective_func, initial_position, dtype)
    f, g = f_g(x)
    s_hist, y_hist, rho_hist = [], [], []
    gamma = 1.0
    is_converge = False
    for _ in range(int(max_iters)):
        if float(jnp.max(jnp.abs(g))) < tolerance_grad:
            is_converge = True
            break
        # two-loop recursion (N&W Alg. 7.4) over the last m pairs
        q = g
        alphas = []
        for s, y, rho in zip(reversed(s_hist), reversed(y_hist),
                             reversed(rho_hist)):
            a = rho * float(jnp.dot(s, q))
            alphas.append(a)
            q = q - a * y
        r = gamma * q
        for (s, y, rho), a in zip(zip(s_hist, y_hist, rho_hist),
                                  reversed(alphas)):
            b = rho * float(jnp.dot(y, r))
            r = r + (a - b) * s
        p = -r
        d0 = float(jnp.dot(g, p))
        if d0 >= 0:
            s_hist, y_hist, rho_hist = [], [], []
            p, d0 = -g, -float(jnp.dot(g, g))
        alpha, f_new, g_new, _ok = _strong_wolfe(
            f_g, x, p, float(f), d0, initial_step_length,
            int(max_line_search_iters))
        s = alpha * p
        if float(jnp.max(jnp.abs(s))) < tolerance_change:
            is_converge = True
            x, f, g = x + s, f_new, g_new
            break
        y = g_new - g
        sy = float(jnp.dot(s, y))
        if sy > 1e-10:
            s_hist.append(s)
            y_hist.append(y)
            rho_hist.append(1.0 / sy)
            if len(s_hist) > int(history_size):
                s_hist.pop(0)
                y_hist.pop(0)
                rho_hist.pop(0)
            gamma = sy / float(jnp.dot(y, y))
        x, f, g = x + s, f_new, g_new
    return _pack(is_converge, calls[0], x, f, g)
