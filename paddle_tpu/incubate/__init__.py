# lint-tpu: disable-file=L004 -- grandfathered direct jax use; new backend code belongs under core/ ops/ kernels/ static/ distributed/ (README: Repo lint)
"""paddle.incubate (reference: python/paddle/incubate/): autotune config,
segment ops, fused transformer ops, 2:4 sparsity (asp)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401

_autotune_config = {"kernel": {"enable": False},
                    "layout": {"enable": False},
                    "dataloader": {"enable": False}}


def autotune_set_config(config=None):
    """reference: python/paddle/incubate/autotune.py set_config.  Kernel
    autotune maps to XLA's autotuning (latency-hiding scheduler + gemm
    algorithm picking), already on by default."""
    if config:
        _autotune_config.update(config)


set_config = autotune_set_config


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _num_segments(ids_t, data_t):
    """Static segment count: XLA needs a fixed output shape.

    Eager: max(ids)+1 from the concrete values (reference output shape,
    incubate/operators/graph_send_recv semantics).  Under a jit trace the
    ids are tracers, so use len(data) — the tight static BOUND (paddle
    requires sorted non-negative ids, one per row at most), giving the op
    a trace-stable shape at the cost of trailing zero rows."""
    v = ids_t._value
    if isinstance(v, jax.core.Tracer):
        return int(data_t.shape[0])
    import numpy as np

    ids = np.asarray(v)
    return int(ids.max()) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    data, segment_ids = _t(data), _t(segment_ids)
    num = _num_segments(segment_ids, data)
    return apply("segment_sum",
                 lambda v, i: jax.ops.segment_sum(v, i, num_segments=num),
                 data, segment_ids)


def _segment_reduce(name, combiner, init):
    def op(data, segment_ids, name_arg=None):
        data_t, ids_t = _t(data), _t(segment_ids)
        num = _num_segments(ids_t, data_t)

        def _fn(v, i):
            if name == "mean":
                s = jax.ops.segment_sum(v, i, num_segments=num)
                cnt = jax.ops.segment_sum(jnp.ones_like(v), i,
                                          num_segments=num)
                return s / jnp.maximum(cnt, 1)
            if name == "max":
                return jax.ops.segment_max(v, i, num_segments=num)
            return jax.ops.segment_min(v, i, num_segments=num)
        return apply(f"segment_{name}", _fn, data_t, ids_t)
    return op


segment_mean = _segment_reduce("mean", None, 0)
segment_max = _segment_reduce("max", None, -jnp.inf)
segment_min = _segment_reduce("min", None, jnp.inf)


def identity_loss(x, reduction="none"):
    from ..ops import math as m

    if reduction == "mean":
        return m.mean(x)
    if reduction == "sum":
        return m.sum(x)
    return _t(x)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None):
    def _fn(v, src, dst):
        import numpy as np

        gathered = jnp.take(v, src, axis=0)
        n = out_size or v.shape[0]
        return jax.ops.segment_sum(gathered, dst, num_segments=n)
    return apply("graph_send_recv", _fn, _t(x), _t(src_index), _t(dst_index))


def softmax_mask_fuse(x, mask, name=None):
    def _fn(v, m):
        return jax.nn.softmax(v + m, axis=-1)
    return apply("softmax_mask_fuse", _fn, _t(x), _t(mask))


_khop_rng = None


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       return_eids=False, name=None, seed=None):
    """K-hop neighbor sampling over a CSC graph (reference:
    python/paddle/incubate/operators/graph_khop_sampler.py backed by
    graph_khop_sampler_op.cu).  Host-side numpy sampling (graph prep is a
    host workload feeding the device), returns Tensors."""
    import numpy as np

    rowv = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    colv = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes
                       ).reshape(-1)
    global _khop_rng
    if seed is not None:
        rng = np.random.RandomState(seed)
    else:  # fresh draws across calls, seeded once per process
        if _khop_rng is None:
            _khop_rng = np.random.RandomState()
        rng = _khop_rng
    edge_src, edge_dst, eids = [], [], []
    cur = nodes
    seen = list(nodes.tolist())
    index = {int(n): i for i, n in enumerate(seen)}
    for k in sample_sizes:
        nxt = []
        for dst in cur:
            dst = int(dst)
            lo, hi = int(colv[dst]), int(colv[dst + 1])
            neigh = rowv[lo:hi]
            ids = np.arange(lo, hi)
            if 0 < k < len(neigh):
                pick = rng.choice(len(neigh), size=k, replace=False)
                neigh, ids = neigh[pick], ids[pick]
            for n, eid in zip(neigh, ids):
                n = int(n)
                if n not in index:
                    index[n] = len(seen)
                    seen.append(n)
                    nxt.append(n)
                edge_src.append(index[n])
                edge_dst.append(index[dst])
                eids.append(int(eid))
        cur = np.asarray(nxt, dtype=rowv.dtype)
    out = (to_tensor(np.asarray(edge_src, np.int64)),
           to_tensor(np.asarray(edge_dst, np.int64)),
           to_tensor(np.asarray(seen, np.int64)),
           to_tensor(np.asarray([len(seen)], np.int64)))
    if return_eids:
        return out + (to_tensor(np.asarray(eids, np.int64)),)
    return out


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Fused causal (upper-triangle-masked) softmax (reference:
    python/paddle/incubate/operators/softmax_mask_fuse_upper_triangle.py
    over fused_softmax_mask_upper_triangle_op.cu).  On TPU the mask+softmax
    fuses in XLA; flash attention covers the attention hot path."""
    def _fn(v):
        t, s = v.shape[-2], v.shape[-1]
        mask = jnp.tril(jnp.ones((t, s), bool), k=s - t)
        return jax.nn.softmax(jnp.where(mask, v, -1e30), axis=-1)

    return apply("softmax_mask_fuse_upper_triangle", _fn, _t(x))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None, seed=None):
    """One-hop neighbor sampling (reference:
    python/paddle/incubate/operators/graph_sample_neighbors.py).  Returns
    (out_neighbors, out_count[, out_eids]) — neighbors of each input node,
    at most `sample_size` each, concatenated in input order."""
    import numpy as np

    rowv = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    colv = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes
                       ).reshape(-1)
    eidv = None
    if eids is not None:
        eidv = np.asarray(eids.numpy() if isinstance(eids, Tensor) else eids)
    rng = np.random.RandomState(seed) if seed is not None \
        else np.random.RandomState()
    out_n, out_c, out_e = [], [], []
    for dst in nodes:
        lo, hi = int(colv[int(dst)]), int(colv[int(dst) + 1])
        idx = np.arange(lo, hi)
        if 0 < sample_size < len(idx):
            idx = idx[rng.choice(len(idx), size=sample_size, replace=False)]
        out_n.extend(int(v) for v in rowv[idx])
        out_c.append(len(idx))
        if eidv is not None:
            out_e.extend(int(v) for v in eidv[idx])
        elif return_eids:
            out_e.extend(int(v) for v in idx)
    outs = (to_tensor(np.asarray(out_n, np.int64)),
            to_tensor(np.asarray(out_c, np.int32)))
    if return_eids:
        return outs + (to_tensor(np.asarray(out_e, np.int64)),)
    return outs


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Compact-id reindexing of a sampled subgraph (reference:
    python/paddle/incubate/operators/graph_reindex.py).  Input nodes keep
    ids [0, len(x)); unseen neighbors get fresh ids in first-seen order.
    Returns (reindex_src, reindex_dst, out_nodes)."""
    import numpy as np

    xs = np.asarray(x.numpy() if isinstance(x, Tensor) else x).reshape(-1)
    nb = np.asarray(neighbors.numpy()
                    if isinstance(neighbors, Tensor) else neighbors
                    ).reshape(-1)
    cnt = np.asarray(count.numpy() if isinstance(count, Tensor) else count
                     ).reshape(-1)
    index = {}
    order = []
    for n in xs.tolist():
        if n not in index:
            index[n] = len(order)
            order.append(n)
    src = []
    for n in nb.tolist():
        if n not in index:
            index[n] = len(order)
            order.append(n)
        src.append(index[n])
    dst = []
    for i, c in enumerate(cnt.tolist()):
        dst.extend([index[int(xs[i])]] * int(c))
    return (to_tensor(np.asarray(src, np.int64)),
            to_tensor(np.asarray(dst, np.int64)),
            to_tensor(np.asarray(order, np.int64)))


class LookAhead:
    """Lookahead optimizer wrapper (reference:
    python/paddle/incubate/optimizer/lookahead.py): every k steps the slow
    weights move alpha of the way toward the fast weights, and the fast
    weights are reset to the slow weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = {}
        self._steps = 0

    def __getattr__(self, name):
        if name.startswith("inner_optimizer") or name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)

    def _params(self):
        return [p for p, _, _ in self.inner_optimizer._collect_params_grads()]

    def _snapshot_slow(self):
        # slow weights initialize to the CURRENT params (before the fast
        # update), matching the reference's slow-param accumulator init
        for p in self._params():
            if id(p) not in self._slow:
                self._slow[id(p)] = p._value.copy()

    def _lookahead_update(self):
        self._steps += 1
        if self._steps % self.k:
            return
        for p in self._params():
            slow = self._slow[id(p)]
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            # the param gets a COPY: the inner optimizer's jitted update
            # donates the param buffer, which would delete `slow` too
            p._value = slow.copy()

    def step(self):
        self._snapshot_slow()
        self.inner_optimizer.step()
        self._lookahead_update()

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **kw):
        # the reference's minimize also applies the lookahead blend
        self._snapshot_slow()
        out = self.inner_optimizer.minimize(loss, *a, **kw)
        self._lookahead_update()
        return out


class ModelAverage:
    """Running average of parameters for evaluation (reference:
    python/paddle/incubate/optimizer/modelaverage.py): accumulates sums of
    params per step; apply() swaps in the average, restore() swaps back.
    The reference's windowed accumulators (min/max_average_window) bound
    the window; average_window_rate scales it with steps taken."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.average_window_rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._parameters = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p._value)
                     for p in self._parameters}
        self._num = 0
        self._backup = None

    def step(self):
        window = max(self.min_average_window,
                     min(self.max_average_window,
                         int(self.average_window_rate * (self._num + 1))
                         or 1))
        if self._num >= window:
            # restart the window (reference folds old sums; decaying
            # restart keeps the average tracking recent weights)
            for p in self._parameters:
                self._sum[id(p)] = self._sum[id(p)] / self._num
            self._num = 1
        for p in self._parameters:
            self._sum[id(p)] = self._sum[id(p)] + p._value
        self._num += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._parameters}
        for p in self._parameters:
            if self._num:
                p._value = (self._sum[id(p)] / self._num).astype(
                    p._value.dtype)
        from contextlib import contextmanager

        @contextmanager
        def ctx():
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameters:
                p._value = self._backup[id(p)]
            self._backup = None


from . import autotune  # noqa: E402,F401
