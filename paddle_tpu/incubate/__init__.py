"""paddle.incubate (reference: python/paddle/incubate/): autotune config,
segment ops, fused transformer ops, 2:4 sparsity (asp)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor
from . import nn  # noqa: F401
from . import asp  # noqa: F401

_autotune_config = {"kernel": {"enable": False},
                    "layout": {"enable": False},
                    "dataloader": {"enable": False}}


def autotune_set_config(config=None):
    """reference: python/paddle/incubate/autotune.py set_config.  Kernel
    autotune maps to XLA's autotuning (latency-hiding scheduler + gemm
    algorithm picking), already on by default."""
    if config:
        _autotune_config.update(config)


set_config = autotune_set_config


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def segment_sum(data, segment_ids, name=None):
    def _fn(v, ids):
        n = int(jax.core.get_aval(ids).shape[0]) if False else None
        num = jnp.max(ids) + 1 if not hasattr(ids, "aval") else None
        # static segment count required under jit: use data length bound
        return jax.ops.segment_sum(v, ids, num_segments=None)
    # eager only when num_segments dynamic
    import numpy as np

    ids = np.asarray(_t(segment_ids).numpy())
    num = int(ids.max()) + 1 if ids.size else 0
    return apply("segment_sum",
                 lambda v, i: jax.ops.segment_sum(v, i, num_segments=num),
                 _t(data), _t(segment_ids))


def _segment_reduce(name, combiner, init):
    def op(data, segment_ids, name_arg=None):
        import numpy as np

        ids = np.asarray(_t(segment_ids).numpy())
        num = int(ids.max()) + 1 if ids.size else 0

        def _fn(v, i):
            one_hot = jax.nn.one_hot(i, num, dtype=v.dtype)
            if name == "mean":
                s = jax.ops.segment_sum(v, i, num_segments=num)
                cnt = jax.ops.segment_sum(jnp.ones_like(v), i,
                                          num_segments=num)
                return s / jnp.maximum(cnt, 1)
            if name == "max":
                return jax.ops.segment_max(v, i, num_segments=num)
            return jax.ops.segment_min(v, i, num_segments=num)
        return apply(f"segment_{name}", _fn, _t(data), _t(segment_ids))
    return op


segment_mean = _segment_reduce("mean", None, 0)
segment_max = _segment_reduce("max", None, -jnp.inf)
segment_min = _segment_reduce("min", None, jnp.inf)


def identity_loss(x, reduction="none"):
    from ..ops import math as m

    if reduction == "mean":
        return m.mean(x)
    if reduction == "sum":
        return m.sum(x)
    return _t(x)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None):
    def _fn(v, src, dst):
        import numpy as np

        gathered = jnp.take(v, src, axis=0)
        n = out_size or v.shape[0]
        return jax.ops.segment_sum(gathered, dst, num_segments=n)
    return apply("graph_send_recv", _fn, _t(x), _t(src_index), _t(dst_index))


def softmax_mask_fuse(x, mask, name=None):
    def _fn(v, m):
        return jax.nn.softmax(v + m, axis=-1)
    return apply("softmax_mask_fuse", _fn, _t(x), _t(mask))


_khop_rng = None


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       return_eids=False, name=None, seed=None):
    """K-hop neighbor sampling over a CSC graph (reference:
    python/paddle/incubate/operators/graph_khop_sampler.py backed by
    graph_khop_sampler_op.cu).  Host-side numpy sampling (graph prep is a
    host workload feeding the device), returns Tensors."""
    import numpy as np

    rowv = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    colv = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes
                       ).reshape(-1)
    global _khop_rng
    if seed is not None:
        rng = np.random.RandomState(seed)
    else:  # fresh draws across calls, seeded once per process
        if _khop_rng is None:
            _khop_rng = np.random.RandomState()
        rng = _khop_rng
    edge_src, edge_dst, eids = [], [], []
    cur = nodes
    seen = list(nodes.tolist())
    index = {int(n): i for i, n in enumerate(seen)}
    for k in sample_sizes:
        nxt = []
        for dst in cur:
            dst = int(dst)
            lo, hi = int(colv[dst]), int(colv[dst + 1])
            neigh = rowv[lo:hi]
            ids = np.arange(lo, hi)
            if 0 < k < len(neigh):
                pick = rng.choice(len(neigh), size=k, replace=False)
                neigh, ids = neigh[pick], ids[pick]
            for n, eid in zip(neigh, ids):
                n = int(n)
                if n not in index:
                    index[n] = len(seen)
                    seen.append(n)
                    nxt.append(n)
                edge_src.append(index[n])
                edge_dst.append(index[dst])
                eids.append(int(eid))
        cur = np.asarray(nxt, dtype=rowv.dtype)
    out = (to_tensor(np.asarray(edge_src, np.int64)),
           to_tensor(np.asarray(edge_dst, np.int64)),
           to_tensor(np.asarray(seen, np.int64)),
           to_tensor(np.asarray([len(seen)], np.int64)))
    if return_eids:
        return out + (to_tensor(np.asarray(eids, np.int64)),)
    return out


from . import autotune  # noqa: E402,F401
