"""paddle.incubate.autotune (reference: python/paddle/incubate/autotune.py
set_config: kernel/layout/dataloader tuning switches backed by the C++
autotune cache — phi/kernels/autotune/).

TPU mapping: "kernel" tuning = Pallas block-size search for the flash
attention / rms-norm kernels (cached per shape), "layout" is XLA's domain
(no-op kept for parity), "dataloader" tunes num_workers by timing.
"""
from __future__ import annotations

import json

from . import _autotune_config

__all__ = ["set_config"]


def set_config(config=None):
    """Accepts a dict or a JSON file path (reference accepts both)."""
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if config:
        for key, val in config.items():
            cur = _autotune_config.setdefault(key, {})
            if isinstance(val, dict):
                cur.update(val)
            else:
                _autotune_config[key] = val
    return dict(_autotune_config)


def get_config():
    return dict(_autotune_config)
