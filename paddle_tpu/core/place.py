"""Device placement.

Capability parity with the reference Place system
(/root/reference/paddle/phi/common/place.h, python/paddle/device) with TPU as
the first-class device.  A Place names a JAX device; "tpu" maps to whatever
accelerator platform the PJRT client exposes (tpu, or cpu when running the
virtual-device test configuration).
"""
from __future__ import annotations

import functools

import jax


class Place:
    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = _devices_for(self.device_type)
        if self.device_id >= len(devs):
            raise RuntimeError(
                f"device {self.device_type}:{self.device_id} not available "
                f"({len(devs)} present)"
            )
        return devs[self.device_id]


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("cpu", device_id)


class CUDAPlace(Place):
    """Migration shim (reference: paddle/phi/common/place.h GPUPlace):
    code written against CUDAPlace runs unmodified with the device id
    mapping onto the accelerator (TPU) of the same index."""

    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPinnedPlace(Place):
    """Pinned-host shim: host staging buffers are PJRT-managed on TPU."""

    def __init__(self):
        super().__init__("cpu", 0)


class CustomPlace(Place):
    def __init__(self, device_type: str = "tpu", device_id: int = 0):
        super().__init__(device_type, device_id)


class XPUPlace(CUDAPlace):
    pass


class NPUPlace(CUDAPlace):
    pass


class MLUPlace(CUDAPlace):
    pass


class IPUPlace(CUDAPlace):
    def __init__(self):
        super().__init__(0)


@functools.lru_cache(maxsize=None)
def _accelerator_platform() -> str:
    """The platform name of the default (accelerator-preferred) backend."""
    return jax.devices()[0].platform


def _devices_for(device_type: str):
    if device_type == "tpu":
        # "tpu" means the accelerator backend; under the CPU test config this
        # is the (possibly virtual multi-device) cpu platform.
        return jax.devices()
    return jax.devices(device_type)


_current_place: Place = None


def set_device(device: str) -> Place:
    """paddle.device.set_device analog: "tpu", "tpu:0", "cpu"."""
    global _current_place
    if ":" in device:
        dev_type, idx = device.split(":")
        place = Place(dev_type, int(idx))
    else:
        place = Place(device, 0)
    place.jax_device()  # validate
    _current_place = place
    return place


def get_device() -> str:
    p = _get_current_place()
    return f"{p.device_type}:{p.device_id}"


def _get_current_place() -> Place:
    global _current_place
    if _current_place is None:
        plat = _accelerator_platform()
        _current_place = Place("tpu" if plat != "cpu" else "cpu", 0)
    return _current_place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role and is always present
    return True


def device_count() -> int:
    # paddle.device.cuda.device_count() is the count THIS process can
    # place tensors on — under jax.distributed that is the local set,
    # not the global fleet (H112)
    return len(jax.local_devices())
