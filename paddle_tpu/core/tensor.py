"""The framework Tensor: a JAX array plus autograd metadata.

Capability analog of the reference eager Tensor
(/root/reference/paddle/phi/api/include/tensor.h:83 paddle::experimental::Tensor
+ /root/reference/paddle/fluid/eager/autograd_meta.h:61 AutogradMeta), with
paddle semantics: `stop_gradient` defaults True for plain tensors and False
for Parameters; `.grad` accumulates on leaves; in-place ops rebind the
underlying buffer (XLA arrays are immutable — rebinding preserves tape
correctness because each op is functional).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dispatch
from .dtype import convert_dtype, get_default_dtype, to_np
from .place import Place, _get_current_place


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_output_index",
        "_hooks",
        "name",
        "persistable",
        "trainable",
        "_version",
        "__weakref__",
        "__dict__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._output_index = 0
        self._hooks = []
        self.name = name
        self.persistable = False
        self.trainable = True
        # In-place version counter (reference: eager VariableWrapper
        # inplace_version checking): bumped by every in-place mutation;
        # the tape compares it against the version recorded at op time and
        # raises instead of producing silently wrong gradients.
        self._version = 0

    @property
    def inplace_version(self):
        return self._version

    # ---------------------------------------------------------------- shape
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return convert_dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    @property
    def place(self) -> Place:
        return _get_current_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    # ------------------------------------------------------------- host I/O
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.numpy())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        """Iterate the leading dim (reference Tensor iteration).  MUST
        be explicit: without it python falls back to the __getitem__
        sequence protocol, and jnp's CLIPPED out-of-range indexing never
        raises IndexError — `for row in tensor` spun forever."""
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(self._value.shape[0]))

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            data = np.array2string(self.numpy(), precision=6, separator=", ")
        except Exception:
            data = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
            f"{grad_info},\n       {data})"
        )

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from . import tape

        tape.run_backward([self], [grad_tensor] if grad_tensor is not None else None,
                          retain_graph=retain_graph)

    def register_hook(self, hook):
        if self._grad_node is not None:
            self._grad_node.out_hooks.setdefault(self._output_index, []).append(hook)

            node, idx = self._grad_node, self._output_index

            class _Removable:
                def remove(self_inner):
                    node.out_hooks[idx].remove(hook)

            return _Removable()
        self._hooks.append(hook)
        hooks = self._hooks

        class _Removable:
            def remove(self_inner):
                hooks.remove(hook)

        return _Removable()

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value))
        else:
            self.grad = None

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .. import ops

        return ops.assign(self)

    # ----------------------------------------------------------- conversion
    def astype(self, dtype) -> "Tensor":
        from .. import ops

        return ops.cast(self, dtype)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # device moves are no-ops in the single-client PJRT model; dtype casts real
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a not in ("cpu", "tpu", "gpu") and ":" not in a:
                dtype = a
        if dtype is not None:
            return self.astype(dtype)
        return self

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    # ----------------------------------------------------------- in-place
    def _rebind(self, new_tensor: "Tensor"):
        """In-place semantics over immutable XLA buffers: take over the new
        value and its position in the autograd graph.

        This is the RECORDED in-place path (setitem, add_, ...): the op's
        grad node legitimately consumed the pre-mutation tensor, so swap a
        snapshot (old value, old graph position, old version) into the
        node's input records — backward and double-grad then see the value
        the op actually read, while the version bump still flags any OTHER
        node that consumed this tensor before the mutation."""
        node = new_tensor._grad_node
        if node is not None and node.input_tensors:
            for i, t in enumerate(node.input_tensors):
                if t is self:
                    snap = Tensor(self._value,
                                  stop_gradient=self.stop_gradient)
                    snap._grad_node = self._grad_node
                    snap._output_index = self._output_index
                    snap._version = self._version
                    node.input_tensors[i] = snap
                    node.input_versions[i] = self._version
        self._value = new_tensor._value
        self._grad_node = new_tensor._grad_node
        self._output_index = new_tensor._output_index
        if not new_tensor.stop_gradient:
            self.stop_gradient = False
        self._version += 1
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        elif isinstance(value, np.ndarray):
            value = jnp.asarray(value, dtype=self._value.dtype)
        self._value = value
        self._version += 1
        return self

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        self._version += 1
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        self._version += 1
        return self

    def copy_(self, other, blocking=True):
        src = other._value if isinstance(other, Tensor) else jnp.asarray(other)
        self._value = jnp.asarray(src, dtype=self._value.dtype)
        self._version += 1
        return self

    # __getitem__/__setitem__ and arithmetic operators are attached by
    # paddle_tpu.ops.monkey_patch() at import time, mirroring the reference's
    # monkey-patching of math ops onto the C++ tensor
    # (/root/reference/python/paddle/tensor/__init__.py).


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, persistable, like
    /root/reference/python/paddle/fluid/framework.py Parameter."""

    def __init__(self, value, name: Optional[str] = None, trainable: bool = True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _convert_data(data, dtype=None):
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(to_np(dtype))
        return v
    if isinstance(data, (list, tuple)):
        data = np.asarray(data)
        if data.dtype == np.float64 and dtype is None:
            dtype = get_default_dtype()
    if isinstance(data, np.ndarray):
        if dtype is None and data.dtype == np.float64:
            # paddle default: python floats -> default dtype
            pass
        return jnp.asarray(data, dtype=to_np(dtype) if dtype else None)
    if isinstance(data, (int, np.integer)):
        # paddle defaults python ints to int64; int32 is the TPU-native width
        return jnp.asarray(data, dtype=to_np(dtype) if dtype else jnp.int32)
    if isinstance(data, (float, np.floating)):
        return jnp.asarray(data, dtype=to_np(dtype) if dtype else to_np(get_default_dtype()))
    if isinstance(data, (bool, np.bool_)):
        return jnp.asarray(data, dtype=to_np(dtype) if dtype else jnp.bool_)
    if isinstance(data, complex):
        return jnp.asarray(data, dtype=to_np(dtype) if dtype else jnp.complex64)
    return jnp.asarray(data, dtype=to_np(dtype) if dtype else None)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor analog."""
    value = _convert_data(data, dtype)
    return Tensor(value, stop_gradient=stop_gradient)
