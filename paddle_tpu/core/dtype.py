"""Data types for the TPU-native framework.

Mirrors the capability of the reference dtype system
(/root/reference/paddle/phi/common/data_type.h) — fp16/bf16/complex as
first-class dtypes — but is expressed directly over numpy/JAX dtypes, since
XLA is the only backend.  bfloat16 is the TPU-preferred half type.
"""
from __future__ import annotations

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes

    bfloat16_np = np.dtype(ml_dtypes.bfloat16)
    float8_e4m3 = np.dtype(ml_dtypes.float8_e4m3fn)
    float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)
except Exception:  # pragma: no cover
    bfloat16_np = np.dtype("float32")
    float8_e4m3 = np.dtype("float32")
    float8_e5m2 = np.dtype("float32")


class DType:
    """A framework dtype: thin, hashable wrapper over a numpy dtype.

    Compares equal to its string name ("float32"), to the numpy dtype, and to
    other DType instances so user code can pass any of the three.
    """

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or _ALIASES.get(other) == self.name
        try:
            return np.dtype(other) == self.np_dtype
        except TypeError:
            return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    @property
    def is_floating_point(self):
        return self.np_dtype.kind == "f" or self.name in (
            "bfloat16",
            "float8_e4m3fn",
            "float8_e5m2",
        )

    @property
    def is_complex(self):
        return self.np_dtype.kind == "c"

    @property
    def is_integer(self):
        return self.np_dtype.kind in ("i", "u")

    @property
    def itemsize(self):
        return self.np_dtype.itemsize


float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", bfloat16_np)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
int8 = DType("int8", np.int8)
uint8 = DType("uint8", np.uint8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
bool_ = DType("bool", np.bool_)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
float8_e4m3fn = DType("float8_e4m3fn", float8_e4m3)
float8_e5m2_t = DType("float8_e5m2", float8_e5m2)

_ALL = [
    float16, bfloat16, float32, float64, int8, uint8, int16, int32, int64,
    bool_, complex64, complex128, float8_e4m3fn, float8_e5m2_t,
]
_BY_NAME = {d.name: d for d in _ALL}
_ALIASES = {"float": "float32", "double": "float64", "half": "float16",
            "int": "int32", "long": "int64", "bool_": "bool"}


def convert_dtype(dtype) -> DType:
    """Normalize str / numpy dtype / DType / jax dtype to a framework DType."""
    if dtype is None:
        return None
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _BY_NAME:
            return _BY_NAME[name]
        raise ValueError(f"unknown dtype {dtype!r}")
    npd = np.dtype(dtype)
    for d in _ALL:
        if d.np_dtype == npd:
            return d
    raise ValueError(f"unsupported dtype {dtype!r}")


def to_np(dtype):
    """Framework/str dtype -> numpy dtype usable by jax.numpy."""
    d = convert_dtype(dtype)
    return None if d is None else d.np_dtype


_default_dtype = float32


def set_default_dtype(dtype):
    global _default_dtype
    d = convert_dtype(dtype)
    if not d.is_floating_point:
        raise TypeError("default dtype must be floating point")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype.name
