"""Autograd tape: GradNode graph + backward engine.

TPU-native analog of the reference eager autograd
(/root/reference/paddle/fluid/eager/backward.cc:529 RunBackward,
grad_node_info.h:165 GradNodeBase, imperative/basic_engine.cc:267
PrepareDeps): reverse traversal with dependency counting and cotangent
accumulation.  Each GradNode owns one jax VJP closure (residuals = saved
tensors, the TensorWrapper analog); processing a node frees its residuals
unless retain_graph is set.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


class GradNode:
    """One recorded op: holds the vjp closure and links to producer nodes."""

    __slots__ = (
        "name",
        "vjp_fn",
        "out_avals",
        "single_output",
        "pending",
        "edges",
        "out_hooks",
        "__weakref__",
    )

    def __init__(self, name: str, vjp_fn):
        self.name = name
        self.vjp_fn = vjp_fn
        self.out_avals: List[Tuple[tuple, Any]] = []
        self.single_output = True
        self.pending: Optional[List[Any]] = None
        # edges[i] corresponds to the i-th differentiable input:
        #   ("node", producer_node, out_index) or ("leaf", tensor)
        self.edges: List[tuple] = []
        self.out_hooks: Dict[int, list] = {}

    def finalize(self, out_avals, single_output, inputs):
        self.out_avals = out_avals
        self.single_output = single_output
        self.pending = [None] * len(out_avals)
        for t in inputs:
            if t._grad_node is not None:
                self.edges.append(("node", t._grad_node, t._output_index))
            else:
                self.edges.append(("leaf", t))

    def accumulate(self, idx: int, cotangent):
        if self.pending[idx] is None:
            self.pending[idx] = cotangent
        else:
            self.pending[idx] = self.pending[idx] + cotangent

    def assembled_cotangents(self):
        cots = []
        for i, (shape, dtype) in enumerate(self.out_avals):
            c = self.pending[i]
            if c is None:
                c = _zero_cotangent(shape, dtype)
            for hook in self.out_hooks.get(i, ()):
                out = hook(_wrap(c))
                if out is not None:
                    c = _unwrap(out)
            cots.append(c)
        return cots

    def release(self):
        self.vjp_fn = None
        self.pending = [None] * len(self.out_avals)


def _wrap(raw):
    from .tensor import Tensor

    return Tensor(raw, stop_gradient=True)


def _unwrap(t):
    from .tensor import Tensor

    return t._value if isinstance(t, Tensor) else t


def _accumulate_leaf_grad(tensor, cotangent):
    from .tensor import Tensor

    c = cotangent
    for hook in tensor._hooks:
        out = hook(_wrap(c))
        if out is not None:
            c = _unwrap(out)
    if tensor.grad is None:
        tensor.grad = Tensor(c, stop_gradient=True)
    else:
        tensor.grad = Tensor(tensor.grad._value + c, stop_gradient=True)


def _discover(roots):
    """BFS the node graph; return (all nodes, in-degree per node)."""
    in_deg: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    stack = list(roots)
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes[id(node)] = node
        for kind, *rest in node.edges:
            if kind == "node":
                prod = rest[0]
                in_deg[id(prod)] = in_deg.get(id(prod), 0) + 1
                stack.append(prod)
    return nodes, in_deg


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 capture: Optional[Dict[int, Any]] = None,
                 capture_points: Optional[Dict[Tuple[int, int], list]] = None):
    """Reverse-mode sweep from `tensors`.

    capture/capture_points support the functional paddle.grad API: when a
    target tensor is an intermediate, its fully-assembled cotangent is
    recorded at (producer node, output index) processing time.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_val = jnp.ones(t.shape, t._value.dtype)
        else:
            g_val = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                _accumulate_leaf_grad(t, g_val)
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to run backward through the graph a second time "
                "(pass retain_graph=True the first time)."
            )
        node.accumulate(t._output_index, g_val)
        roots.append(node)

    if not roots:
        return

    nodes, in_deg = _discover(roots)
    queue = deque(n for n in nodes.values() if in_deg.get(id(n), 0) == 0)
    processed = set()

    while queue:
        node = queue.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))

        cots = node.assembled_cotangents()
        if capture_points:
            for (nid, idx), sinks in capture_points.items():
                if nid == id(node):
                    for sink in sinks:
                        capture[sink] = cots[idx]
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad node {node.name} already released; use retain_graph=True"
            )
        in_cots = node.vjp_fn(cots[0] if node.single_output else tuple(cots))

        for (kind, *rest), cot in zip(node.edges, in_cots):
            if cot is None or (hasattr(cot, "dtype") and cot.dtype == jax.dtypes.float0):
                continue
            if kind == "leaf":
                tensor = rest[0]
                if capture is not None and id(tensor) in capture:
                    prev = capture[id(tensor)]
                    capture[id(tensor)] = cot if prev is None else prev + cot
                else:
                    _accumulate_leaf_grad(tensor, cot)
            else:
                prod, idx = rest
                prod.accumulate(idx, cot)
                in_deg[id(prod)] -= 1
                if in_deg[id(prod)] == 0:
                    queue.append(prod)

        if not retain_graph:
            node.release()
        else:
            node.pending = [None] * len(node.out_avals)
