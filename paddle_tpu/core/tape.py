"""Autograd tape: GradNode graph + backward engine.

TPU-native analog of the reference eager autograd
(/root/reference/paddle/fluid/eager/backward.cc:529 RunBackward,
grad_node_info.h:165 GradNodeBase, imperative/basic_engine.cc:267
PrepareDeps): reverse traversal with dependency counting and cotangent
accumulation.  Each GradNode owns one jax VJP closure (residuals = saved
tensors, the TensorWrapper analog); processing a node frees its residuals
unless retain_graph is set.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


class GradNode:
    """One recorded op: holds the vjp closure and links to producer nodes."""

    __slots__ = (
        "name",
        "vjp_fn",
        "out_avals",
        "single_output",
        "pending",
        "edges",
        "out_hooks",
        "input_tensors",
        "input_versions",
        "grad_raw_fn",
        "record_vjp",
        "__weakref__",
    )

    def __init__(self, name: str, vjp_fn):
        self.name = name
        self.vjp_fn = vjp_fn
        self.out_avals: List[Tuple[tuple, Any]] = []
        self.single_output = True
        self.pending: Optional[List[Any]] = None
        # edges[i] corresponds to the i-th differentiable input:
        #   ("node", producer_node, out_index) or ("leaf", tensor)
        self.edges: List[tuple] = []
        self.out_hooks: Dict[int, list] = {}
        # double-grad support (reference GeneralGrad + double-grad ops,
        # /root/reference/paddle/fluid/eager/backward.cc:37): the recorded
        # op's pure function + its differentiable input Tensors, so a
        # create_graph sweep can re-run the vjp THROUGH dispatch and give
        # the cotangents their own grad nodes.  Memory note: raw_fn's
        # closure (and these Tensor refs) pin the op's inputs for the
        # node's lifetime — for most ops the jax vjp residuals already do;
        # the increment is limited to residual-free ops (add & co) and is
        # bounded by the graph's lifetime (released after backward).
        self.input_tensors: Optional[List[Any]] = None
        self.input_versions: Optional[List[int]] = None
        self.grad_raw_fn = None
        self.record_vjp = None  # custom recordable vjp (PyLayer)

    def finalize(self, out_avals, single_output, inputs):
        self.out_avals = out_avals
        self.single_output = single_output
        self.pending = [None] * len(out_avals)
        self.input_tensors = list(inputs)
        self.input_versions = [t._version for t in inputs]
        for t in inputs:
            if t._grad_node is not None:
                self.edges.append(("node", t._grad_node, t._output_index))
            else:
                self.edges.append(("leaf", t))

    def accumulate(self, idx: int, cotangent):
        if self.pending[idx] is None:
            self.pending[idx] = cotangent
        else:
            self.pending[idx] = self.pending[idx] + cotangent

    def assembled_cotangents(self, as_tensor=False):
        cots = []
        for i, (shape, dtype) in enumerate(self.out_avals):
            c = self.pending[i]
            if c is None:
                c = _zero_cotangent(shape, dtype)
                if as_tensor:  # float0 zeros wrap too: PyLayer backward's
                    c = _wrap(c)  # contract is Tensors for every cotangent
            for hook in self.out_hooks.get(i, ()):
                out = hook(_wrap(c))
                if out is not None:
                    c = out if as_tensor else _unwrap(out)
            cots.append(c)
        return cots

    def check_versions(self):
        """Raise if any input was mutated in place after recording
        (reference: eager VariableWrapper inplace_version check)."""
        if not self.input_tensors:
            return
        for t, v0 in zip(self.input_tensors, self.input_versions):
            if t._version != v0:
                raise RuntimeError(
                    f"a tensor consumed by op '{self.name}' was modified "
                    f"by an inplace operation after being recorded "
                    f"(version {t._version} vs {v0}); gradients would be "
                    "wrong — clone() before mutating, or mutate after "
                    "backward")

    def release(self):
        self.vjp_fn = None
        self.pending = [None] * len(self.out_avals)
        self.input_tensors = None
        self.input_versions = None
        self.grad_raw_fn = None
        self.record_vjp = None


def _wrap(raw):
    from .tensor import Tensor

    if isinstance(raw, Tensor):
        return raw
    return Tensor(raw, stop_gradient=True)


def _unwrap(t):
    from .tensor import Tensor

    return t._value if isinstance(t, Tensor) else t


def _cot_dtype(c):
    from .tensor import Tensor

    return c._value.dtype if isinstance(c, Tensor) else c.dtype


def _accumulate_leaf_grad(tensor, cotangent):
    from .tensor import Tensor

    c = cotangent
    for hook in tensor._hooks:
        out = hook(_wrap(c))
        if out is not None:
            c = out if isinstance(cotangent, Tensor) else _unwrap(out)
    if isinstance(c, Tensor):  # create_graph sweep: grads keep their graph
        tensor.grad = c if tensor.grad is None else tensor.grad + c
    elif tensor.grad is None:
        tensor.grad = Tensor(c, stop_gradient=True)
    else:
        tensor.grad = Tensor(tensor.grad._value + c, stop_gradient=True)


def _record_vjp_via_apply(node, cot_tensors):
    """Compute node's vjp THROUGH dispatch so the resulting cotangents are
    themselves recorded (the double-grad op of the reference's codegen'd
    GradNode pairs).  Re-runs the op's forward for the residuals — the
    standard recompute formulation of grad-of-grad."""
    from . import dispatch

    raw_fn = node.grad_raw_fn
    n_in = len(node.input_tensors)
    out_avals = node.out_avals
    single = node.single_output
    inexact = [i for i, (_, d) in enumerate(out_avals)
               if jnp.issubdtype(d, jnp.inexact)]
    passed = [cot_tensors[i] for i in inexact]

    def op(*vals):
        primals, cvals = vals[:n_in], list(vals[n_in:])
        cots = []
        for i, (shape, dtype) in enumerate(out_avals):
            if jnp.issubdtype(dtype, jnp.inexact):
                cots.append(cvals.pop(0))
            else:
                cots.append(np.zeros(shape, jax.dtypes.float0))
        _, vjp = jax.vjp(raw_fn, *primals)
        return tuple(vjp(cots[0] if single else tuple(cots)))

    with dispatch.enable_grad_ctx():
        res = dispatch.apply(f"{node.name}_grad", op,
                             *node.input_tensors, *passed)
    return list(res) if isinstance(res, tuple) else [res]


def _discover(roots):
    """BFS the node graph; return (all nodes, in-degree per node)."""
    in_deg: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    stack = list(roots)
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes[id(node)] = node
        for kind, *rest in node.edges:
            if kind == "node":
                prod = rest[0]
                in_deg[id(prod)] = in_deg.get(id(prod), 0) + 1
                stack.append(prod)
    return nodes, in_deg


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 capture: Optional[Dict[int, Any]] = None,
                 capture_points: Optional[Dict[Tuple[int, int], list]] = None,
                 create_graph: bool = False):
    """Reverse-mode sweep from `tensors`.

    capture/capture_points support the functional paddle.grad API: when a
    target tensor is an intermediate, its fully-assembled cotangent is
    recorded at (producer node, output index) processing time.

    create_graph: cotangents flow as Tensors and each node's vjp runs
    THROUGH dispatch (recorded), so the produced gradients are themselves
    differentiable (reference: eager double-grad ops + GeneralGrad,
    backward.cc:37).  Implies retain_graph.
    """
    from .tensor import Tensor

    if create_graph:
        retain_graph = True
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_val = jnp.ones(t.shape, t._value.dtype)
            if create_graph:
                g_val = _wrap(g_val)
        elif create_graph:
            g_val = g if isinstance(g, Tensor) else _wrap(jnp.asarray(g))
        else:
            g_val = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                _accumulate_leaf_grad(t, g_val)
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to run backward through the graph a second time "
                "(pass retain_graph=True the first time)."
            )
        node.accumulate(t._output_index, g_val)
        roots.append(node)

    if not roots:
        return

    nodes, in_deg = _discover(roots)
    queue = deque(n for n in nodes.values() if in_deg.get(id(n), 0) == 0)
    processed = set()

    # create_graph: the whole sweep (cotangent adds included) must record,
    # even when the caller sits inside no_grad.
    import contextlib

    from . import dispatch

    grad_ctx = (dispatch.enable_grad_ctx() if create_graph
                else contextlib.nullcontext())
    with grad_ctx:
        while queue:
            node = queue.popleft()
            if id(node) in processed:
                continue
            processed.add(id(node))

            cots = node.assembled_cotangents(as_tensor=create_graph)
            if capture_points:
                for (nid, idx), sinks in capture_points.items():
                    if nid == id(node):
                        for sink in sinks:
                            capture[sink] = cots[idx]
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"grad node {node.name} already released; use "
                    "retain_graph=True")
            node.check_versions()
            if create_graph:
                if node.record_vjp is not None:
                    in_cots = node.record_vjp(cots)
                elif node.grad_raw_fn is not None and \
                        node.input_tensors is not None:
                    in_cots = _record_vjp_via_apply(node, cots)
                else:
                    raise RuntimeError(
                        f"op '{node.name}' does not support create_graph "
                        "(no recordable vjp)")
            else:
                in_cots = node.vjp_fn(
                    cots[0] if node.single_output else tuple(cots))

            for (kind, *rest), cot in zip(node.edges, in_cots):
                if cot is None or _cot_dtype(cot) == jax.dtypes.float0:
                    continue
                if kind == "leaf":
                    tensor = rest[0]
                    if capture is not None:
                        if id(tensor) in capture:
                            prev = capture[id(tensor)]
                            capture[id(tensor)] = (cot if prev is None
                                                   else prev + cot)
                        # else: functional grad (only_inputs) — never
                        # touch .grad of tensors outside `inputs`
                    else:
                        _accumulate_leaf_grad(tensor, cot)
                else:
                    prod, idx = rest
                    prod.accumulate(idx, cot)
                    in_deg[id(prod)] -= 1
                    if in_deg[id(prod)] == 0:
                        queue.append(prod)

            if not retain_graph:
                node.release()
            else:
                node.pending = [None] * len(node.out_avals)
