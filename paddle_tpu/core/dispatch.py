"""Eager op dispatch + grad recording.

This is the TPU-native replacement for the reference dygraph tracer
(/root/reference/paddle/fluid/imperative/tracer.cc:186 TraceOpImpl and the
eager engine /root/reference/paddle/fluid/eager/): every framework op is a
functional JAX computation; when gradients are required we obtain the op's
VJP closure via jax.vjp at call time (one forward execution, residuals live
on device) and record a GradNode on the tape.  There is exactly ONE autograd
engine — no legacy/eager split.

Inside `paddle_tpu.jit.to_static` traces the tape is bypassed entirely:
differentiation of compiled programs happens through jax.grad on the
functionalized program, which is the idiomatic XLA path.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, List

import jax
import jax.numpy as jnp

from . import tape as tape_mod
from .flags import flag


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.in_static_trace = False


_state = _State()

# Static-graph recorder hook: paddle_tpu.static.graph installs a callback
# while static mode is enabled; apply() routes ops that touch symbolic
# Variables to it (the reference's dygraph/static mode switch,
# /root/reference/python/paddle/fluid/framework.py in_dygraph_mode).
NOT_RECORDED = object()  # recorder return value meaning "run eagerly"
_graph_recorder = None


def set_graph_recorder(recorder):
    global _graph_recorder
    prev = _graph_recorder
    _graph_recorder = recorder
    return prev


def is_grad_enabled() -> bool:
    # NB: the tape keeps recording inside to_static traces — jax.vjp over
    # tracers is what lets loss.backward() + optimizer.step() compile into
    # the one traced program.  in_static_trace only gates data-dependent-shape
    # ops (nonzero/unique/...), which must raise under a trace.
    return _state.grad_enabled


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad_ctx():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad_ctx():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def static_trace_guard():
    """Active while jit.to_static traces user code: tape off, ops trace into XLA."""
    prev = _state.in_static_trace
    _state.in_static_trace = True
    try:
        yield
    finally:
        _state.in_static_trace = prev


def in_static_trace() -> bool:
    return _state.in_static_trace


class no_grad:
    """Context manager AND decorator, like paddle.no_grad."""

    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad_ctx():
                return fn(*args, **kwargs)

        return wrapper


def _is_tensor(x):
    from .tensor import Tensor

    return isinstance(x, Tensor)


def _differentiable_dtype(v) -> bool:
    return jnp.issubdtype(jnp.result_type(v), jnp.inexact)


def apply(name: str, fn, *args, _differentiable: bool = True, **attrs):
    """Run op `fn` over args (Tensors possibly nested in lists/tuples) with
    static keyword attrs; wrap outputs in Tensors and record the grad node.
    """
    from .tensor import Tensor

    if _graph_recorder is not None:
        rec = _graph_recorder(name, fn, args, attrs)
        if rec is not NOT_RECORDED:
            return rec

    flat, treedef = jax.tree_util.tree_flatten(
        args, is_leaf=_is_tensor
    )
    tensor_idx = [i for i, leaf in enumerate(flat) if _is_tensor(leaf)]

    record = (
        _differentiable
        and is_grad_enabled()
        and any(
            not flat[i].stop_gradient and _differentiable_dtype(flat[i]._value)
            for i in tensor_idx
        )
    )

    # Partition tensor leaves: differentiable ones become vjp arguments, the
    # rest are closed over as constants.
    diff_idx = [
        i
        for i in tensor_idx
        if record
        and not flat[i].stop_gradient
        and _differentiable_dtype(flat[i]._value)
    ]

    # AMP O1/O2: per-op cast decision (reference: imperative/tracer.cc:224
    # AutoCastInputs / amp_auto_cast.cc).  The cast happens inside raw_fn so
    # the vjp closure differentiates through it.
    amp_np_dtype = None
    try:
        from ..amp import amp_op_dtype

        amp_target = amp_op_dtype(name)
        if amp_target is not None:
            from .dtype import to_np

            amp_np_dtype = to_np(amp_target)
    except ImportError:  # during early package import
        pass

    def _amp_cast(v):
        if amp_np_dtype is not None and jnp.issubdtype(
                jnp.result_type(v), jnp.floating):
            return v.astype(amp_np_dtype)
        return v

    def raw_fn(*diff_vals):
        new_flat = list(flat)
        for pos, v in zip(diff_idx, diff_vals):
            new_flat[pos] = _amp_cast(v)
        for i in tensor_idx:
            if i not in diff_idx:
                new_flat[i] = _amp_cast(new_flat[i]._value)
        new_args = jax.tree_util.tree_unflatten(treedef, new_flat)
        return fn(*new_args, **attrs)

    if record:
        diff_vals = [flat[i]._value for i in diff_idx]
        out_raw, vjp_fn = jax.vjp(raw_fn, *diff_vals)
        node = tape_mod.GradNode(name, vjp_fn)
        node.grad_raw_fn = raw_fn  # double-grad: recordable vjp recompute
    else:
        out_raw = raw_fn()
        node = None

    single = not isinstance(out_raw, (tuple, list))
    out_list = [out_raw] if single else list(out_raw)

    outputs: List[Any] = []
    for i, o in enumerate(out_list):
        diff_out = record and _differentiable_dtype(o)
        t = Tensor(o, stop_gradient=not diff_out)
        if record:
            t._grad_node = node
            t._output_index = i
        outputs.append(t)

    if node is not None:
        node.finalize(
            out_avals=[(tuple(o.shape), o.dtype) for o in out_list],
            single_output=single,
            inputs=[flat[i] for i in diff_idx],
        )

    if flag("check_nan_inf"):
        _check_nan_inf(name, outputs)

    return outputs[0] if single else tuple(outputs)


def _check_nan_inf(name, outputs):
    """FLAGS_check_nan_inf analog (reference: details/nan_inf_utils_detail,
    hooked into every op run at operator.cc:1270).  Eager: host check.
    Compiled: a device-side finite-reduction feeds a debug callback that
    raises — the compiled-mode debug path the reference gets from its
    per-op nan/inf CUDA kernels."""
    import numpy as np

    for t in outputs:
        v = t._value
        if not jnp.issubdtype(v.dtype, jnp.inexact):
            continue
        if isinstance(v, jax.core.Tracer):
            ok = jnp.isfinite(v.astype(jnp.float32)).all()

            def _host_assert(ok_val, _name=name):
                if not bool(ok_val):
                    raise FloatingPointError(
                        f"op {_name} produced nan/inf (compiled mode)")

            jax.debug.callback(_host_assert, ok)
            continue
        arr = np.asarray(v.astype(jnp.float32))
        if not np.isfinite(arr).all():
            raise FloatingPointError(f"op {name} produced nan/inf")
